"""unicore-lint: every rule must fire on a seeded violation and stay
silent on clean code (ISSUE 1 acceptance).

Trace rules (UL001-UL006) get tiny fixture programs audited through
``jax.make_jaxpr`` / ``jit.lower``; source rules (UL101-UL105) get
fixture files written to tmp_path.  The flagship-config integration
audit (the CI gate) runs at the end; the multi-variant mesh sweep is
the only trace-heavy case and stays seconds-fast at audit shapes.
"""

import json
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unicore_tpu.analysis.findings import (
    Finding,
    load_baseline,
    split_baselined,
    write_baseline,
)
from unicore_tpu.analysis.source_lint import lint_paths
from unicore_tpu.analysis.trace_audit import (
    audit_donation,
    audit_jaxpr,
    audit_sharding_coverage,
)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------
# UL001 upcast-leak
# ---------------------------------------------------------------------

def test_upcast_leak_fires_on_mixed_dot():
    def leaky(x, w, bias):
        h = x + bias           # bf16 + f32 -> promotes h to f32
        return h @ w           # f32 @ bf16 -> mixed-dtype dot_general

    x = jnp.ones((256, 128), jnp.bfloat16)
    w = jnp.ones((128, 64), jnp.bfloat16)
    bias = jnp.ones((256, 128), jnp.float32)
    found = audit_jaxpr(jax.make_jaxpr(leaky)(x, w, bias))
    assert "UL001" in rules_of(found)


def test_upcast_leak_silent_on_clean_bf16_matmul():
    def clean(x, w):
        # bf16 operands with fp32 MXU accumulation: the correct idiom
        return jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    x = jnp.ones((256, 128), jnp.bfloat16)
    w = jnp.ones((128, 64), jnp.bfloat16)
    assert audit_jaxpr(jax.make_jaxpr(clean)(x, w)) == []


def test_upcast_leak_pedantic_flags_elementwise_chain():
    def leaky(x, bias):
        return x + bias        # convert(x)->f32 feeds f32 add

    x = jnp.ones((256, 128), jnp.bfloat16)
    bias = jnp.ones((256, 128), jnp.float32)
    jaxpr = jax.make_jaxpr(leaky)(x, bias)
    assert "UL001" in rules_of(audit_jaxpr(jaxpr, pedantic=True))
    # default mode: elementwise-only promotion is not reported (the
    # repo's deliberate fp32 islands match the same jaxpr pattern)
    assert audit_jaxpr(jaxpr) == []


# ---------------------------------------------------------------------
# UL002 giant-intermediate
# ---------------------------------------------------------------------

def test_giant_intermediate_fires_on_materialized_scores():
    T = 2048

    def attn_scores(q, k):  # [B,H,T,D] x 2 -> [B,H,T,T] fp32 scores
        return jnp.einsum("bhtd,bhsd->bhts", q, k)

    q = jnp.ones((2, 4, T, 64), jnp.float32)
    found = audit_jaxpr(jax.make_jaxpr(attn_scores)(q, q), seq_len=T)
    assert "UL002" in rules_of(found)
    assert any("O(T^2)" in f.message for f in found)


def test_giant_intermediate_fires_on_absolute_budget():
    def blow_up(x):
        return jnp.broadcast_to(x, (512, 1024, 1024))  # 2 GiB fp32

    x = jnp.ones((1024, 1024), jnp.float32)
    found = audit_jaxpr(jax.make_jaxpr(blow_up)(x))
    assert "UL002" in rules_of(found)


def test_giant_intermediate_silent_on_flash_sized_buffers():
    def small(q, k):
        return jnp.einsum("bhtd,bhsd->bhts", q, k)  # tiny T

    q = jnp.ones((2, 4, 64, 16), jnp.float32)
    assert audit_jaxpr(jax.make_jaxpr(small)(q, q), seq_len=64) == []


# ---------------------------------------------------------------------
# UL003 donation-miss
# ---------------------------------------------------------------------

def _state_step(state, x):
    return {"p": state["p"] + x.sum()}, (x * 2).sum()


def test_donation_miss_fires_without_donate_argnums():
    state = {"p": jnp.zeros((512, 1024))}  # 2 MiB > the 1 MiB threshold
    x = jnp.ones((8, 8))
    lowered = jax.jit(_state_step).lower(state, x)
    assert rules_of(audit_donation(lowered)) == {"UL003"}


def test_donation_silent_with_donate_argnums():
    state = {"p": jnp.zeros((512, 1024))}
    x = jnp.ones((8, 8))
    lowered = jax.jit(_state_step, donate_argnums=(0,)).lower(state, x)
    assert audit_donation(lowered) == []


def test_donation_silent_below_min_bytes():
    lowered = jax.jit(_state_step).lower(
        {"p": jnp.zeros((4, 4))}, jnp.ones((4, 4))
    )
    assert audit_donation(lowered) == []


# ---------------------------------------------------------------------
# UL004 host-callback
# ---------------------------------------------------------------------

def test_host_callback_fires_on_debug_print():
    def noisy(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    found = audit_jaxpr(jax.make_jaxpr(noisy)(1.0))
    assert "UL004" in rules_of(found)


def test_host_callback_fires_on_pure_callback():
    def hostcall(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x,
        )

    found = audit_jaxpr(jax.make_jaxpr(hostcall)(jnp.ones((4,))))
    assert "UL004" in rules_of(found)


def test_host_callback_silent_on_pure_step():
    found = audit_jaxpr(jax.make_jaxpr(lambda x: x * 2 + 1)(jnp.ones((4,))))
    assert found == []


# ---------------------------------------------------------------------
# UL005 sharding-hole (needs the virtual 8-device CPU mesh)
# ---------------------------------------------------------------------

def _mesh(fsdp=1, tensor=1):
    devs = np.asarray(jax.devices()[:8]).reshape(
        8 // (fsdp * tensor), fsdp, 1, tensor
    )
    return jax.sharding.Mesh(devs, ("data", "fsdp", "seq", "tensor"))


def _named(mesh, *spec):
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(*spec)
    )


def test_sharding_hole_fires_on_replicated_leaf_under_fsdp():
    mesh = _mesh(fsdp=2)
    shapes = {"params": {"w": jax.ShapeDtypeStruct((256, 64), jnp.float32)}}
    shardings = {"params": {"w": _named(mesh)}}  # fully replicated
    found = audit_sharding_coverage(mesh, shardings, shapes)
    assert rules_of(found) == {"UL005"}
    assert "fsdp" in found[0].message


def test_sharding_hole_fires_on_disengaged_tensor_spec():
    mesh = _mesh(tensor=2)
    # embed_tokens/embedding is DESIGNATED tensor-parallel (vocab dim)
    shapes = {"params": {"embed_tokens": {
        "embedding": jax.ShapeDtypeStruct((64, 64), jnp.float32)}}}
    shardings = {"params": {"embed_tokens": {"embedding": _named(mesh)}}}
    found = audit_sharding_coverage(mesh, shardings, shapes)
    assert [f.severity for f in found] == ["error"]
    assert "failed to engage" in found[0].message


def test_sharding_hole_warns_on_indivisible_tensor_dim():
    mesh = _mesh(tensor=2)
    shapes = {"params": {"embed_tokens": {
        "embedding": jax.ShapeDtypeStruct((63, 64), jnp.float32)}}}
    shardings = {"params": {"embed_tokens": {"embedding": _named(mesh)}}}
    found = audit_sharding_coverage(mesh, shardings, shapes)
    assert [f.severity for f in found] == ["warning"]


def test_sharding_hole_silent_when_sharded_or_undesignated():
    mesh = _mesh(fsdp=2, tensor=2)
    shapes = {
        "params": {
            "embed_tokens": {
                "embedding": jax.ShapeDtypeStruct((64, 64), jnp.float32)},
            "w": jax.ShapeDtypeStruct((256, 64), jnp.float32),
            "tiny": jax.ShapeDtypeStruct((8,), jnp.float32),
        }
    }
    shardings = {
        "params": {
            "embed_tokens": {
                "embedding": _named(mesh, ("tensor", "fsdp"), None)},
            "w": _named(mesh, "fsdp", None),
            "tiny": _named(mesh),  # small leaves legally replicate
        }
    }
    assert audit_sharding_coverage(mesh, shardings, shapes) == []


# ---------------------------------------------------------------------
# UL006 fp64-leak
# ---------------------------------------------------------------------

def test_fp64_leak_fires_under_x64():
    from jax.experimental import enable_x64

    with enable_x64(True):
        jaxpr = jax.make_jaxpr(
            lambda x: x * np.float64(2.0)
        )(jnp.ones((4,), jnp.float64))
    assert "UL006" in rules_of(audit_jaxpr(jaxpr))


def test_fp64_leak_silent_on_fp32():
    jaxpr = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones((4,), jnp.float32))
    assert audit_jaxpr(jaxpr) == []


# ---------------------------------------------------------------------
# source lint fixtures (UL101-UL105)
# ---------------------------------------------------------------------

def _lint_snippet(tmp_path, name, code):
    f = tmp_path / name
    f.write_text(textwrap.dedent(code))
    return lint_paths([str(f)])


def test_jit_missing_donation_fires(tmp_path):
    found = _lint_snippet(tmp_path, "step.py", """
        import jax
        def train_step(state, batch):
            return state, batch
        step = jax.jit(train_step)
    """)
    assert "UL101" in rules_of(found)


def test_jit_missing_donation_fires_on_decorator_forms(tmp_path):
    found = _lint_snippet(tmp_path, "step.py", """
        import functools
        import jax
        @jax.jit
        def train_step(state, batch):
            return state, batch
        @functools.partial(jax.jit, static_argnums=(2,))
        def train_step_accum(state, batch, n):
            return state, batch
    """)
    assert sum(1 for f in found if f.rule == "UL101") == 2


def test_jit_missing_donation_silent_on_donating_decorator(tmp_path):
    found = _lint_snippet(tmp_path, "step.py", """
        import functools
        import jax
        @functools.partial(jax.jit, donate_argnums=(0,))
        def train_step(state, batch):
            return state, batch
        @jax.jit
        def eval_step(state, batch):  # not a train step: no rule
            return batch
    """)
    assert "UL101" not in rules_of(found)


def test_jit_missing_donation_silent_with_donation(tmp_path):
    found = _lint_snippet(tmp_path, "step.py", """
        import jax
        def train_step(state, batch):
            return state, batch
        step = jax.jit(train_step, donate_argnums=(0,))
        evaluate = jax.jit(lambda s, b: s)  # not a train step: no rule
    """)
    assert "UL101" not in rules_of(found)


def test_numpy_in_jit_fires(tmp_path):
    found = _lint_snippet(tmp_path, "step.py", """
        import jax
        import numpy as np
        @jax.jit
        def train_step(state, batch):
            return state, np.asarray(batch)
    """)
    assert "UL102" in rules_of(found)


def test_numpy_in_jit_silent_on_metadata_and_unjitted(tmp_path):
    found = _lint_snippet(tmp_path, "step.py", """
        import jax
        import numpy as np
        @jax.jit
        def train_step(state, batch):
            n = np.prod(batch.shape)  # metadata-only: allowed
            return state, batch / n
        def host_helper(x):
            return np.asarray(x)  # not jitted: allowed
    """)
    assert "UL102" not in rules_of(found)


def test_unseeded_dataset_rng_fires(tmp_path):
    found = _lint_snippet(tmp_path, "my_dataset.py", """
        import random
        import numpy as np
        def __getitem__(self, index):
            a = np.random.rand(4)
            b = random.randint(0, 3)
            g = np.random.RandomState()
            return a, b, g
    """)
    assert sum(1 for f in found if f.rule == "UL103") == 3


def test_unseeded_dataset_rng_silent_inside_numpy_seed(tmp_path):
    found = _lint_snippet(tmp_path, "my_dataset.py", """
        import numpy as np
        from unicore_tpu.data import data_utils
        def __getitem__(self, index):
            with data_utils.numpy_seed(self.seed, self.epoch, index):
                a = np.random.rand(4)
            gen = np.random.RandomState(42)
            return a, gen
    """)
    assert "UL103" not in rules_of(found)


def test_blocking_fetch_fires_and_suppression_works(tmp_path):
    found = _lint_snippet(tmp_path, "lib.py", """
        def run(x, y):
            x.block_until_ready()
            v = y.item()
            ok = y.item()  # unicore-lint: disable=UL104
            return v, ok
    """)
    assert sum(1 for f in found if f.rule == "UL104") == 2


def test_blocking_fetch_silent_in_stats_slow_path(tmp_path):
    d = tmp_path / "logging"
    d.mkdir()
    f = d / "meters.py"
    f.write_text("def fmt(v):\n    return v.item()\n")
    assert lint_paths([str(f)]) == []


def test_dropout_dead_rate_fires(tmp_path):
    found = _lint_snippet(tmp_path, "model.py", """
        from unicore_tpu.ops.dropout import dropout
        def f(x, rng):
            return dropout(x, 0.001, rng)
    """)
    assert "UL105" in rules_of(found)


def test_dropout_dead_rate_matches_op_at_boundary(tmp_path):
    # r = 1/512 rounds to q = 256 (identity) in ops/dropout.py — the
    # lint must agree with the op's quantization, not a re-derived band
    found = _lint_snippet(tmp_path, "model.py", """
        from unicore_tpu.ops.dropout import dropout
        def f(x, rng):
            return dropout(x, 0.001953125, rng)
    """)
    assert "UL105" in rules_of(found)


def test_dropout_dead_rate_silent_on_representable_rates(tmp_path):
    found = _lint_snippet(tmp_path, "model.py", """
        from unicore_tpu.ops.dropout import dropout
        def f(x, rng):
            return dropout(x, 0.1, rng), dropout(x, 0.0, rng)
    """)
    assert "UL105" not in rules_of(found)


# ---------------------------------------------------------------------
# baseline / suppression mechanics
# ---------------------------------------------------------------------

def test_baseline_roundtrip_suppresses_known_findings(tmp_path):
    f1 = Finding("UL104", "blocking-fetch", "error", "a.py:10", "msg one")
    f2 = Finding("UL104", "blocking-fetch", "error", "b.py:20", "msg two")
    path = tmp_path / "baseline.json"
    write_baseline(str(path), [f1])
    fps = load_baseline(str(path))
    # line numbers must not churn the baseline
    moved = Finding("UL104", "blocking-fetch", "error", "a.py:99", "msg one")
    new, suppressed = split_baselined([moved, f2], fps)
    assert [f.location for f in suppressed] == ["a.py:99"]
    assert [f.location for f in new] == ["b.py:20"]


def test_baseline_missing_file_is_empty():
    assert load_baseline("/nonexistent/baseline.json") == set()


# ---------------------------------------------------------------------
# integration: the repo itself must be clean, and the flagship config
# must trace-audit clean over the dryrun meshes (the CI gate)
# ---------------------------------------------------------------------

def _repo_root():
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_source_lint_clean_within_baseline():
    import os

    from unicore_tpu.analysis.cli import DEFAULT_LINT_ROOTS

    # the default file set must cover the tool entry points, not just
    # the library (ISSUE 4 satellite: examples/ + serve/cli.py + tools/)
    assert set(DEFAULT_LINT_ROOTS) >= {
        "unicore_tpu", "unicore_tpu_cli", "examples", "tools", "bench.py"
    }
    root = _repo_root()
    roots = [os.path.join(root, d) for d in DEFAULT_LINT_ROOTS]
    findings = lint_paths(roots, rel_to=root)
    fps = load_baseline(os.path.join(root, "tools", "lint_baseline.json"))
    new, _ = split_baselined(findings, fps)
    assert new == [], "\n".join(f.render() for f in new)


def test_flagship_bert_trace_audit_clean():
    import os

    from unicore_tpu.analysis.scenarios import audit_bert_config

    findings, reports = audit_bert_config(
        os.path.join(_repo_root(), "examples", "bert"), n_devices=8
    )
    assert findings == [], "\n".join(f.render() for f in findings)
    ran = [r["variant"] for r in reports if "mesh" in r]
    assert ran == ["dp", "fsdp2", "tp2", "seq2", "tp2_fsdp2"], reports


def test_fused_head_audit_silent_fused_fires_materialized():
    """ISSUE 10 acceptance: with UL002's budget pinned to the head's
    full-logits byte size (rows * vocab * 4), the DEFAULT (fused
    chunked) train step must be silent on every pass-3 mesh variant —
    no intermediate that large exists in forward or backward — while
    the materialized head (--fused-lm-head off) must fire on each, the
    tripwire proving the budget bites at audit shapes."""
    import os

    from unicore_tpu.analysis.scenarios import (
        MESH_VARIANTS,
        PASS3_VARIANTS,
        ZERO1_VARIANTS,
        audit_fused_head_memory,
    )

    variants = [v for v in MESH_VARIANTS + ZERO1_VARIANTS
                if v[0] in PASS3_VARIANTS]
    results = audit_fused_head_memory(
        os.path.join(_repo_root(), "examples", "bert"),
        variants=variants, n_devices=8,
    )
    assert sorted(results) == sorted(PASS3_VARIANTS), results
    for name, per in results.items():
        assert per["fused"] == [], (
            name, "\n".join(f.render() for f in per["fused"]))
        assert any(f.rule == "UL002" for f in per["naive"]), (
            name, "materialized head did not trip the logits budget")


def test_trainer_trace_audit_catches_seeded_sharding_hole():
    """End-to-end negative control: force a hole through the REAL
    trainer artifacts and assert the audit sees it (guards against the
    audit silently auditing the wrong tree)."""
    import os

    from unicore_tpu.analysis.scenarios import (
        build_bert_scenario,
        restore_globals,
        snapshot_globals,
    )
    from unicore_tpu.analysis.trace_audit import audit_sharding_coverage

    snap = snapshot_globals()
    try:
        trainer, samples, _ = build_bert_scenario(
            os.path.join(_repo_root(), "examples", "bert"),
            {"fsdp_size": 2}, jax.devices()[:8],
        )
        art = trainer.trace_train_step(samples)
        # sabotage: claim every leaf is replicated
        rep = jax.sharding.NamedSharding(
            trainer.mesh, jax.sharding.PartitionSpec()
        )
        broken = jax.tree_util.tree_map(lambda _: rep,
                                        art["state_shardings"])
        found = audit_sharding_coverage(trainer.mesh, broken, art["state"])
        assert "UL005" in rules_of(found)
    finally:
        restore_globals(snap)


def test_cli_module_runs_lint_only():
    proc = subprocess.run(
        [sys.executable, "-m", "unicore_tpu.analysis", "--no-trace", "-q"],
        cwd=_repo_root(), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_json_report_and_exit_code(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(x):\n    return x.block_until_ready()\n"
    )
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "unicore_tpu.analysis", "--no-trace", "-q",
         "--no-baseline", "--lint-root", str(bad), "--json", str(out)],
        cwd=_repo_root(), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 1
    report = json.loads(out.read_text())
    assert report["counts"]["new"] == 1
    assert report["new_findings"][0]["rule"] == "UL104"


# ---------------------------------------------------------------------
# UL106 where-nan-grad
# ---------------------------------------------------------------------

def test_where_nan_grad_fires_on_risky_branches(tmp_path):
    found = _lint_snippet(tmp_path, "model.py", """
        import jax.numpy as jnp
        def f(x, n, d):
            a = jnp.where(x > 0, jnp.sqrt(x), 0.0)
            b = jnp.where(d != 0, n / d, 0.0)
            c = jnp.where(x > 0, x ** 0.5, 0.0)
            return a, b, c
    """)
    assert sum(1 for f in found if f.rule == "UL106") == 3


def test_where_nan_grad_silent_on_clamped_and_plain(tmp_path):
    found = _lint_snippet(tmp_path, "model.py", """
        import jax.numpy as jnp
        def f(x, n, d, keep, keep_prob, mask):
            a = jnp.where(x > 0, jnp.sqrt(jnp.maximum(x, 1e-6)), 0.0)
            b = jnp.where(mask, x, -1e9)              # plain branches
            c = jnp.where(keep, n / keep_prob, 0.0)   # denom not guarded
            d2 = jnp.where(x > 0, x * 2.0, x / 4.0)   # constant denom
            return a, b, c, d2
    """)
    assert "UL106" not in rules_of(found)


def test_where_nan_grad_ignores_module_alias_overlap(tmp_path):
    # 'jnp' appearing in both the condition and a denominator is NOT a
    # shared value, and the documented clamp fix silences the div half
    found = _lint_snippet(tmp_path, "model.py", """
        import jax.numpy as jnp
        def f(self, x, m, w, n, d, eps):
            a = jnp.where(jnp.all(m), x / jnp.sum(w), 0.0)
            b = jnp.where(d > eps, n / jnp.maximum(d, eps), 0.0)
            # attribute ROOTS are not shared values: self.eps vs
            # self.temperature must not collide on 'self'
            c = jnp.where(m > self.eps, x / self.temperature, 0.0)
            # the sanctioned clamp fix silences the pow form too
            e = jnp.where(x > 0, jnp.maximum(x, eps) ** 0.5, 0.0)
            return a, b, c, e
    """)
    assert "UL106" not in rules_of(found)


def test_where_nan_grad_tracks_jnp_import_forms(tmp_path):
    found = _lint_snippet(tmp_path, "model.py", """
        from jax import numpy as jn
        def f(x):
            return jn.where(x > 0, jn.log(x), 0.0)
    """)
    assert "UL106" in rules_of(found)


# ---------------------------------------------------------------------
# UL107 swallowed-io-error
# ---------------------------------------------------------------------

def test_swallowed_io_error_fires(tmp_path):
    found = _lint_snippet(tmp_path, "ckpt.py", """
        import os, pickle
        def save(obj, fn):
            try:
                with open(fn, "wb") as fh:
                    pickle.dump(obj, fh)
            except Exception:
                pass
        def sweep(paths):
            for p in paths:
                try:
                    os.remove(p)
                except:
                    continue
    """)
    assert sum(1 for f in found if f.rule == "UL107") == 2


def test_swallowed_io_error_silent_on_sanctioned_forms(tmp_path):
    found = _lint_snippet(tmp_path, "ckpt.py", """
        import os, pickle, logging
        logger = logging.getLogger(__name__)
        def narrow(fn):
            try:
                os.remove(fn)
            except FileNotFoundError:
                pass  # deliberate: prune races are benign
        def logged(obj, fn):
            try:
                with open(fn, "wb") as fh:
                    pickle.dump(obj, fh)
            except Exception:
                logger.error("save failed", exc_info=True)
                raise
        def no_io(x):
            try:
                return float(x)
            except Exception:
                pass
    """)
    assert "UL107" not in rules_of(found)


def test_swallowed_io_error_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "ckpt.py", """
        import os
        def f(p):
            try:
                os.remove(p)
            except Exception:  # unicore-lint: disable=UL107
                pass
    """)
    assert "UL107" not in rules_of(found)


# ---------------------------------------------------------------------
# UL108 sync-in-step-loop
# ---------------------------------------------------------------------

def test_sync_in_step_loop_fires(tmp_path):
    found = _lint_snippet(tmp_path, "loop.py", """
        import jax
        def train(trainer, batches):
            for b in batches:
                out = trainer.train_step(b)
                stats = jax.device_get(out)           # per-step sync
                trainer.save_checkpoint("last.pt", {})  # sync save
            return stats
        def drive(trainer, stream):
            staged = next(stream, None)
            while staged is not None:
                out = trainer.train_step(staged)
                out.block_until_ready()
                staged = next(stream, None)
    """)
    assert sum(1 for f in found if f.rule == "UL108") == 3


def test_sync_in_step_loop_silent_outside_and_in_plain_loops(tmp_path):
    found = _lint_snippet(tmp_path, "loop.py", """
        import jax
        def train(trainer, batches):
            # the sanctioned shape: dispatch inside, fetch at the end
            for b in batches:
                out = trainer.train_step(b)
            trainer.flush_stats()
            return jax.device_get(out)
        def not_a_step_loop(xs):
            # device_get in a loop that never dispatches train steps
            return [jax.device_get(x) for x in xs]
        def eval_loop(model, batches):
            for b in batches:
                out = model.valid_step(b)
                host = jax.device_get(out)
            return host
        def epochs(trainer, loader):
            # the OUTER loop is not a step loop: train_step only runs
            # in the nested loop, so the per-epoch fetch is the
            # sanctioned real-boundary sync, not a per-step stall
            for epoch in range(3):
                for b in loader:
                    out = trainer.train_step(b)
                stats = jax.device_get(out)
                trainer.save_checkpoint(f"ck{epoch}.pt", stats)
    """)
    assert "UL108" not in rules_of(found)


def test_sync_in_step_loop_inline_suppression_and_closure(tmp_path):
    found = _lint_snippet(tmp_path, "loop.py", """
        import jax
        def train(trainer, batches):
            for b in batches:
                out = trainer.train_step(b)
                x = jax.device_get(out)  # unicore-lint: disable=UL108
        def builder(trainer):
            # a closure DEFINED in a step loop does not run per
            # iteration — its body must not be flagged
            hooks = []
            for phase in ("a", "b"):
                trainer.train_step(None)
                def done(out):
                    return jax.device_get(out)
                hooks.append(done)
            return hooks
    """)
    assert "UL108" not in rules_of(found)


# ---------------------------------------------------------------------
# UL112 sync-on-current-step
# ---------------------------------------------------------------------

def test_sync_on_current_step_fires(tmp_path):
    found = _lint_snippet(tmp_path, "pipeloop.py", """
        import jax
        def train(trainer, batches):
            for b in batches:
                out = trainer.train_step(b)
                loss = out["loss"].item()        # sync on THIS step
            return loss
        def drive(trainer, stream):
            staged = next(stream, None)
            while staged is not None:
                state, stats = trainer.train_step(staged)
                host = jax.device_get(stats)     # current-step fetch
                stats["gnorm"].block_until_ready()
                staged = next(stream, None)
    """)
    assert sum(1 for f in found if f.rule == "UL112") == 3


def test_sync_on_current_step_silent_on_drain_path(tmp_path):
    found = _lint_snippet(tmp_path, "pipeloop.py", """
        import jax
        def train(trainer, batches):
            # the sanctioned lag-K shape: train_step's return IS the
            # lagged host-side stats; flush_stats gives exact counts —
            # syncing on values from the DRAIN path must not fire
            for b in batches:
                out = trainer.train_step(b)
                exact = trainer.flush_stats()
                if exact is not None:
                    exact[0]["loss"].item()
            return jax.device_get(out)           # after the loop: fine
        def rebound_from_drain(trainer, batches):
            # rebinding the SAME name from the drain path launders it:
            # the nearest binding above the sync is flush_stats, not
            # the step call
            for b in batches:
                out = trainer.train_step(b)
                out = trainer.flush_stats()
                if out is not None:
                    out[0]["loss"].item()
            return out
        def manual_lag_one(trainer, batches):
            # reading the PREVIOUS iteration's output before this
            # iteration's dispatch is the manual lag-1 idiom — the
            # value is already on host, nothing stalls
            prev = None
            for b in batches:
                if prev is not None:
                    prev["loss"].item()
                prev = trainer.train_step(b)
            return prev
        def not_a_step_loop(model, xs):
            for x in xs:
                y = model.valid_step(x)
                y.block_until_ready()            # no train_step here
    """)
    assert "UL112" not in rules_of(found)


def test_sync_on_current_step_suppression_and_closure(tmp_path):
    found = _lint_snippet(tmp_path, "pipeloop.py", """
        import jax
        def train(trainer, batches):
            for b in batches:
                out = trainer.train_step(b)
                x = jax.device_get(out)  # unicore-lint: disable=UL112,UL108
        def builder(trainer):
            # a closure DEFINED in the loop does not run per iteration
            hooks = []
            for b in ("a", "b"):
                out = trainer.train_step(b)
                def done():
                    return jax.device_get(out)
                hooks.append(done)
            return hooks
    """)
    assert "UL112" not in rules_of(found)


# ---------------------------------------------------------------------
# UL109 unbounded-queue-growth
# ---------------------------------------------------------------------

def test_unbounded_queue_growth_fires(tmp_path):
    found = _lint_snippet(tmp_path, "server.py", """
        def serve_forever(sched, source, backlog):
            while True:
                req = source.get()
                sched.waiting.append(req)        # no bound, no shed
                backlog.insert(0, req)           # second offender
                sched.admit()
        def drive(sched, reqs):
            for r in reqs:
                retry_queue.appendleft(r)        # third offender
                sched.prepare_decode()
        def poll_then_drain(sched, source, k):
            # the scheduling marker lives in a NESTED loop: the outer
            # while still grows the queue once per serve cycle, so it
            # must classify as the serve loop (regression: the UL108
            # nested-loop exclusion must not apply here)
            while True:
                queue.append(source.get())       # fourth offender
                for _ in range(k):
                    sched.admit()
    """)
    assert sum(1 for f in found if f.rule == "UL109") == 4


def test_unbounded_queue_growth_silent_on_bounded_and_shed(tmp_path):
    found = _lint_snippet(tmp_path, "server.py", """
        def bounded(sched, source, max_waiting):
            while True:
                req = source.get()
                # bound check on the same collection sanctions growth
                if len(sched.waiting) < max_waiting:
                    sched.waiting.append(req)
                sched.admit()
        def drains(sched, source):
            while True:
                sched.waiting.append(source.get())
                sched.waiting.popleft()          # drain path
                sched.admit()
        def sheds(sched, source):
            while True:
                req = source.get()
                sched.waiting.append(req)
                shed_overflow(sched)             # a shed path in sight
                sched.admit()
        def not_a_serve_loop(out, items):
            for x in items:                      # no scheduling markers
                out.append(x)
        def closure_in_loop(sched, reqs):
            hooks = []
            while True:
                sched.admit()
                if len(hooks) > 4:
                    break
                def late(q, r):
                    q.append(r)                  # closure: fresh scope
                hooks.append(late)
    """)
    assert "UL109" not in rules_of(found)


def test_unbounded_queue_growth_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "server.py", """
        def serve_forever(sched, source):
            while True:
                req = source.get()
                sched.waiting.append(req)  # unicore-lint: disable=UL109
                sched.admit()
    """)
    assert "UL109" not in rules_of(found)


# ---------------------------------------------------------------------
# UL111 blocking-in-router-loop
# ---------------------------------------------------------------------

def test_blocking_in_router_loop_fires(tmp_path):
    found = _lint_snippet(tmp_path, "router.py", """
        import time
        def dispatch_loop(replicas, worker):
            while True:
                for eng in replicas:
                    eng.serve_step()
                time.sleep(0.01)                 # pacing stall
                worker.join()                    # parks behind one thread
        def drive(router, home, reqs):
            for req in reqs:
                router.route(req)
                home.generate([req])             # batch-blocking API
        def nested(router, engines):
            # fan-out in a NESTED for: the outer while still blocks
            # once per dispatch cycle, so it classifies (UL109-style
            # subtree semantics, not UL108's nested-loop exclusion)
            while True:
                for eng in engines:
                    eng.serve_step()
                time.sleep(1)                    # fourth offender
    """)
    assert sum(1 for f in found if f.rule == "UL111") == 4


def test_blocking_in_router_loop_silent_cases(tmp_path):
    found = _lint_snippet(tmp_path, "router.py", """
        import time
        def not_a_router_loop(items, worker):
            for x in items:                      # no dispatch markers
                time.sleep(0.01)
                worker.join()
        def str_join_is_fine(router, rows):
            while True:
                router.dispatch(rows)
                label = ",".join(r.id for r in rows)   # one arg: str.join
            return label
        def paced_outside(router, reqs):
            for req in reqs:
                router.route(req)
            time.sleep(0.5)                      # after the loop
        def closure_in_loop(router, hooks):
            while True:
                router.serve_step()
                def later():
                    time.sleep(1)                # fresh scope
                hooks.pop()
                hooks.append(later)
                if not hooks:
                    break
    """)
    assert "UL111" not in rules_of(found)


def test_blocking_in_router_loop_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "router.py", """
        import time
        def dispatch_loop(replicas):
            while True:
                for eng in replicas:
                    eng.serve_step()
                time.sleep(0.01)  # unicore-lint: disable=UL111
    """)
    assert "UL111" not in rules_of(found)


# ---------------------------------------------------------------------
# UL113 unguarded-replica-step
# ---------------------------------------------------------------------

def test_unguarded_replica_step_fires(tmp_path):
    found = _lint_snippet(tmp_path, "router.py", """
        def fleet_loop(engines):
            while True:
                for rid in sorted(engines):
                    engines[rid].serve_step()      # subscripted replica
        def fan_out(replicas):
            for eng in replicas:                   # replica-ish iterable
                eng.serve_step()
        def two_receivers(a, b, work):
            while work:
                a.serve_step()                     # two distinct replicas
                b.serve_step()
                work.pop()
    """)
    assert sum(1 for f in found if f.rule == "UL113") == 4


def test_unguarded_replica_step_silent_cases(tmp_path):
    found = _lint_snippet(tmp_path, "router.py", """
        def guarded_fleet_loop(engines, health, evict):
            # the sanctioned shape: typed fault handling around the step
            while True:
                for rid in sorted(engines):
                    try:
                        engines[rid].serve_step()
                    except Exception as exc:
                        health.record_exception(rid, exc)
                        evict(rid)
        def health_recorded(replicas, health):
            # health recording in the loop also sanctions a bare step
            for rid, eng in replicas.items():
                eng.serve_step()
                health.observe(rid, eng.load_snapshot(), eng.has_work())
        def self_driver(self):
            # an engine driving ITSELF is its own run loop, not a fleet
            while self.serve_step():
                pass
        def solo_harness(eng, n):
            # a bench/test harness driving ONE local engine: no fan-out
            for _ in range(n):
                eng.serve_step()
        def no_loop(eng2):
            eng2.serve_step()                      # not in a loop at all
    """)
    assert "UL113" not in rules_of(found)


def test_unguarded_replica_step_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "router.py", """
        def fleet_loop(engines):
            for rid in sorted(engines):
                engines[rid].serve_step()  # unicore-lint: disable=UL113
    """)
    assert "UL113" not in rules_of(found)


def test_unguarded_replica_step_fleet_package_clean():
    # the shipped fleet tier must BE the sanctioned shape: every
    # replica step routed through the guarded/health-recording helper
    import os

    import unicore_tpu.fleet as fleet_pkg

    root = os.path.dirname(fleet_pkg.__file__)
    found = lint_paths([root])
    assert "UL113" not in rules_of(found), [
        (f.location, f.message) for f in found if f.rule == "UL113"]


# ---------------------------------------------------------------------
# UL110 unguarded-dataset-io
# ---------------------------------------------------------------------

def test_unguarded_dataset_io_fires(tmp_path):
    # filename marks it a dataset file; raw IO in __getitem__ with no
    # typed re-raise = 3 findings (open+loads, lmdb get), and a broad
    # swallow in __iter__ = 1 more
    found = _lint_snippet(tmp_path, "raw_dataset.py", """
        import pickle
        class Raw:
            def __getitem__(self, idx):
                with open(self.paths[idx], "rb") as f:
                    return pickle.loads(f.read())
        class Db:
            def __getitem__(self, idx):
                return self._env.begin().get(self._keys[idx])
        class It:
            def __iter__(self):
                for p in self.paths:
                    try:
                        yield pickle.load(open(p, "rb"))
                    except Exception:
                        continue
    """)
    ul110 = [f for f in found if f.rule == "UL110"]
    # Raw: open + pickle.loads; Db: lmdb get; It: the swallow (the IO
    # inside the try is separately unguarded too — no re-raise)
    assert len(ul110) >= 4, found


def test_unguarded_dataset_io_silent_on_typed_reraise(tmp_path):
    found = _lint_snippet(tmp_path, "rec_dataset.py", """
        import pickle
        from unicore_tpu.data.resilient import DataIntegrityError
        class Store:
            def __getitem__(self, idx):
                try:
                    return pickle.loads(self._bytes(idx))
                except pickle.UnpicklingError as e:
                    raise DataIntegrityError(f"record {idx} torn") from e
            def helper_outside_fetch(self, p):
                return pickle.load(open(p, "rb"))  # not a fetch body
        class NoIo:
            def __getitem__(self, idx):
                return self.items[idx]
    """)
    assert "UL110" not in rules_of(found)


def test_unguarded_dataset_io_ignores_non_dataset_files(tmp_path):
    found = _lint_snippet(tmp_path, "container.py", """
        import pickle
        class Box:
            def __getitem__(self, idx):
                return pickle.loads(self.blobs[idx])
    """)
    assert "UL110" not in rules_of(found)


def test_unguarded_dataset_io_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "raw_dataset.py", """
        import pickle
        class Raw:
            def __getitem__(self, idx):
                return pickle.loads(self.blobs[idx])  # unicore-lint: disable=UL110
    """)
    assert "UL110" not in rules_of(found)


# ---------------------------------------------------------------------
# Pass 3: HLO parsing primitives (pure text, no compile)
# ---------------------------------------------------------------------

def test_parse_replica_groups_iota_and_explicit():
    from unicore_tpu.analysis.hlo_audit import parse_replica_groups

    assert parse_replica_groups("replica_groups=[4,2]<=[8],") == tuple(
        frozenset(p) for p in [(0, 1), (2, 3), (4, 5), (6, 7)]
    )
    # reshape+transpose iota: arange(8).reshape(4,2).T -> strided groups
    assert parse_replica_groups(
        "replica_groups=[2,4]<=[4,2]T(1,0),"
    ) == (frozenset({0, 2, 4, 6}), frozenset({1, 3, 5, 7}))
    assert parse_replica_groups(
        "replica_groups={{0,2,4,6},{1,3,5,7}}, use_global"
    ) == (frozenset({0, 2, 4, 6}), frozenset({1, 3, 5, 7}))
    assert parse_replica_groups("replica_groups={}", 4) == (
        frozenset({0, 1, 2, 3}),
    )
    assert parse_replica_groups("no groups here") is None


_HLO_SNIPPET = """
  %all-gather = f32[64,64]{1,0} all-gather(f32[32,64]{1,0} %p), \
channel_id=1, replica_groups=[4,2]<=[8], dimensions={0}, \
metadata={op_name="jit(step)/fwd/dot_general"}
  %all-reduce = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %d), \
channel_id=2, replica_groups=[1,8]<=[8], to_apply=%add
  %ar-done = f32[8]{0} all-reduce-done(f32[8]{0} %x)
  %ags = (f32[32,64]{1,0}, f32[64,64]{1,0}) all-gather-start(\
f32[32,64]{1,0} %p), replica_groups=[4,2]<=[8], dimensions={0}
  %cp = u32[128]{0} collective-permute(u32[128]{0} %y), \
source_target_pairs={{0,1}}
"""


def test_extract_collectives_and_stats():
    from unicore_tpu.analysis.hlo_audit import (
        collective_stats,
        extract_collectives,
    )

    colls = extract_collectives(_HLO_SNIPPET, 8)
    assert [c.kind for c in colls] == [
        "all-gather", "all-reduce", "all-gather", "collective-permute"
    ]
    ag = colls[0]
    assert ag.bytes == 64 * 64 * 4 and ag.is_float
    assert ag.groups == tuple(
        frozenset(p) for p in [(0, 1), (2, 3), (4, 5), (6, 7)]
    )
    assert ag.op_name == "jit(step)/fwd/dot_general"
    # async -start: the result tuple aliases the operand next to the
    # output — count the transfer once (largest component), not summed
    assert colls[2].bytes == 64 * 64 * 4
    stats = collective_stats(colls)
    assert stats["collective_bytes"]["all-gather"] == 2 * 64 * 64 * 4
    assert stats["collective_count"]["collective-permute"] == 1
    assert not colls[3].is_float  # u32 permute


# ---------------------------------------------------------------------
# Pass 3: UL201 unit fixtures (synthetic collectives over a real mesh)
# ---------------------------------------------------------------------

def _coll(kind, nbytes, groups, *, is_float=True, shape="f32[x]"):
    from unicore_tpu.analysis.hlo_audit import Collective

    return Collective(kind=kind, shape=shape, bytes=nbytes,
                      is_float=is_float,
                      groups=tuple(frozenset(g) for g in groups),
                      op_name="test")


def test_ul201_unit_fires_and_stays_silent():
    from unicore_tpu.analysis.hlo_audit import audit_fsdp_collectives

    mesh = _mesh(fsdp=2)  # data=4, fsdp=2: fsdp pairs {0,1},{2,3},...
    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    fsdp_pairs = [(0, 1), (2, 3), (4, 5), (6, 7)]
    healthy = [
        _coll("all-gather", 16384, fsdp_pairs),
        _coll("all-reduce", 16384, [(0, 2, 4, 6), (1, 3, 5, 7)]),
    ]
    assert audit_fsdp_collectives(mesh, healthy, params,
                                  context="t") == []
    # disengaged: only full-mesh all-reduces remain
    dead = [_coll("all-reduce", 16384, [range(8)])]
    found = audit_fsdp_collectives(mesh, dead, params, context="t")
    assert rules_of(found) == {"UL201"}
    assert "disengaged" in found[0].message
    # full-remat: weight-sized all-gather spanning the data axis
    remat = healthy + [_coll("all-gather", 20000, [range(8)])]
    found = audit_fsdp_collectives(mesh, remat, params, context="t")
    assert rules_of(found) == {"UL201"}
    assert "remat" in found[0].message
    # same gather below weight scale: budget territory, not UL201
    small = healthy + [_coll("all-gather", 1024, [range(8)])]
    assert audit_fsdp_collectives(mesh, small, params, context="t") == []
    # dp mesh: rule does not apply
    assert audit_fsdp_collectives(_mesh(), dead, params,
                                  context="t") == []


# ---------------------------------------------------------------------
# Pass 3: UL202/UL203 budget round-trip (unit)
# ---------------------------------------------------------------------

def test_budget_roundtrip_and_regressions(tmp_path):
    from unicore_tpu.analysis import hlo_audit

    path = str(tmp_path / "comms.json")
    fp = "test|fingerprint"
    stats = {"collective_bytes": {"all-gather": 1000, "all-reduce": 500},
             "peak_bytes": 10000}
    hlo_audit.update_budget_entries(path, fp, {"s1": stats})
    budgets = hlo_audit.load_budgets(path)
    entry = hlo_audit.budget_entry(budgets, fp, "s1")
    assert hlo_audit.audit_comms_budget("s1", stats, entry) == []
    assert hlo_audit.audit_memory_budget("s1", 10000, entry) == []
    # within tolerance: 4% over passes, >5% fails
    ok = {"collective_bytes": {"all-gather": 1040, "all-reduce": 500}}
    assert hlo_audit.audit_comms_budget("s1", ok, entry) == []
    bad = {"collective_bytes": {"all-gather": 1100, "all-reduce": 500}}
    found = hlo_audit.audit_comms_budget("s1", bad, entry)
    assert rules_of(found) == {"UL202"}
    # a collective kind the budget never saw
    new_kind = {"collective_bytes": {"all-gather": 1000,
                                     "all-to-all": 64}}
    found = hlo_audit.audit_comms_budget("s1", new_kind, entry)
    assert any("all-to-all" in f.message for f in found)
    # a zero-byte committed kind must report, not ZeroDivisionError
    zero_entry = {"collective_bytes": {"all-gather": 0},
                  "peak_bytes": 10000}
    found = hlo_audit.audit_comms_budget(
        "s1", {"collective_bytes": {"all-gather": 64}}, zero_entry
    )
    assert rules_of(found) == {"UL202"}
    # full-surface updates prune scenarios that no longer exist
    hlo_audit.update_budget_entries(path, fp, {"gone": stats})
    assert hlo_audit.prune_budget_entries(path, fp, {"s1"}) == ["gone"]
    assert hlo_audit.budget_entry(
        hlo_audit.load_budgets(path), fp, "s1") is not None
    # memory regression + missing budget
    found = hlo_audit.audit_memory_budget("s1", 11000, entry)
    assert rules_of(found) == {"UL203"}
    found = hlo_audit.audit_memory_budget("s1", 11000, None)
    assert [f.severity for f in found] == ["warning"]
    # stale fingerprints self-invalidate: entries keyed elsewhere unread
    assert hlo_audit.budget_entry(budgets, "other|fp", "s1") is None
    # updating one scenario keeps other fingerprints' sections intact
    hlo_audit.update_budget_entries(path, "other|fp", {"s2": stats})
    budgets = hlo_audit.load_budgets(path)
    assert hlo_audit.budget_entry(budgets, fp, "s1") is not None


# ---------------------------------------------------------------------
# Pass 3: UL204 / UL205 units
# ---------------------------------------------------------------------

def test_ul204_collective_divergence():
    from unicore_tpu.analysis.hlo_audit import audit_sequence_match

    a = [_coll("all-gather", 64, [(0, 1)], shape="f32[64]"),
         _coll("all-reduce", 64, [(0, 1)], shape="f32[64]")]
    b = list(reversed(a))  # order must NOT matter
    assert audit_sequence_match("g", [("s1", a), ("s2", b)]) == []
    c = a + [_coll("all-gather", 64, [(0, 1)], shape="f32[128]")]
    found = audit_sequence_match("g", [("s1", a), ("s3", c)])
    assert rules_of(found) == {"UL204"}
    assert "f32[128]" in found[0].message


def test_ul205_serve_recompiles():
    from unicore_tpu.analysis.hlo_audit import audit_serve_recompiles

    # the unified ragged step's constant two-width surface is clean
    declared = (1, 32)
    width_fn = lambda m: 1 if m <= 1 else 32  # noqa: E731
    assert audit_serve_recompiles(width_fn, declared, 32) == []
    # a broken width fn: one lowering per chunk size
    found = audit_serve_recompiles(lambda m: max(m, 8), declared, 92)
    assert rules_of(found) == {"UL205"}
    # chunk sizes 1..92 through max(m, 8): 85 distinct lowerings
    assert "85 distinct" in found[0].message


# ---------------------------------------------------------------------
# Pass 3 integration: the real compiled fsdp2 step (one compile,
# shared) and the deliberately disengaged spec (ISSUE 4 acceptance)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def fsdp2_compiled():
    import os

    from unicore_tpu.analysis.scenarios import (
        build_bert_scenario,
        restore_globals,
        snapshot_globals,
    )

    snap = snapshot_globals()
    try:
        trainer, samples, _ = build_bert_scenario(
            os.path.join(_repo_root(), "examples", "bert"),
            {"fsdp_size": 2}, jax.devices()[:8],
        )
        art = trainer.trace_train_step(samples)
        compiled = art["lowered"].compile()
        yield trainer, art, compiled
    finally:
        restore_globals(snap)


@pytest.mark.slow  # AOT-compiles the real step; CI's full pytest runs it
def test_ul201_silent_on_healthy_fsdp2(fsdp2_compiled):
    from unicore_tpu.analysis import hlo_audit

    trainer, art, compiled = fsdp2_compiled
    found, stats, colls = hlo_audit.audit_compiled(
        compiled, context="bert/fsdp2", mesh=trainer.mesh,
        params=art["state"]["params"], num_devices=8,
    )
    assert found == [], "\n".join(f.render() for f in found)
    # the compiled step's collectives are real and byte-counted
    assert stats["collective_bytes"].get("all-gather", 0) > 0
    assert stats["peak_bytes"] and stats["peak_bytes"] > 0
    assert any(c.kind == "all-gather" and c.is_float for c in colls)


@pytest.mark.slow  # AOT-compiles the real step; CI's full pytest runs it
def test_ul201_fires_on_disengaged_fsdp_spec():
    """ISSUE 4 acceptance: a deliberately disengaged fsdp spec (state
    installed replicated on an fsdp mesh) must trip UL201 through the
    REAL compile path."""
    import os

    from unicore_tpu.analysis import hlo_audit
    from unicore_tpu.analysis.scenarios import (
        build_bert_scenario,
        restore_globals,
        snapshot_globals,
    )

    snap = snapshot_globals()
    try:
        trainer, samples, _ = build_bert_scenario(
            os.path.join(_repo_root(), "examples", "bert"),
            {"fsdp_size": 2}, jax.devices()[:8],
        )
        trainer.init_state(samples[0])
        rep = jax.sharding.NamedSharding(
            trainer.mesh, jax.sharding.PartitionSpec()
        )
        trainer._state_shardings = jax.tree_util.tree_map(
            lambda _: rep, trainer._state_shardings
        )
        trainer.state = jax.device_put(
            jax.device_get(trainer.state), rep
        )
        art = trainer.trace_train_step(samples)
        compiled = art["lowered"].compile()
        found, _, _ = hlo_audit.audit_compiled(
            compiled, context="bert/fsdp2-disengaged",
            mesh=trainer.mesh, params=art["state"]["params"],
            num_devices=8,
        )
        assert "UL201" in rules_of(found), found
    finally:
        restore_globals(snap)


@pytest.mark.slow  # AOT-compiles the real step; CI's full pytest runs it
def test_real_budget_roundtrip_from_compiled_step(fsdp2_compiled,
                                                  tmp_path):
    """--pass3 budget semantics against the real compiled stats: update
    -> clean; shrink the committed budget -> UL202 + UL203 fail."""
    import json as _json

    from unicore_tpu.analysis import hlo_audit

    _, _, compiled = fsdp2_compiled
    _, stats, _ = hlo_audit.audit_compiled(compiled,
                                           context="bert/fsdp2")
    path = str(tmp_path / "comms.json")
    fp = hlo_audit.pass3_fingerprint()
    hlo_audit.update_budget_entries(path, fp, {"bert/fsdp2": stats})
    entry = hlo_audit.budget_entry(hlo_audit.load_budgets(path), fp,
                                   "bert/fsdp2")
    assert hlo_audit.audit_comms_budget("bert/fsdp2", stats,
                                        entry) == []
    assert hlo_audit.audit_memory_budget(
        "bert/fsdp2", stats["peak_bytes"], entry) == []
    # an exceeded committed budget must fail
    data = _json.load(open(path))
    e = data["budgets"][fp]["bert/fsdp2"]
    e["collective_bytes"] = {
        k: int(v * 0.5) for k, v in e["collective_bytes"].items()
    }
    e["peak_bytes"] = int(e["peak_bytes"] * 0.5)
    _json.dump(data, open(path, "w"))
    entry = hlo_audit.budget_entry(hlo_audit.load_budgets(path), fp,
                                   "bert/fsdp2")
    rules = rules_of(
        hlo_audit.audit_comms_budget("bert/fsdp2", stats, entry)
        + hlo_audit.audit_memory_budget("bert/fsdp2",
                                        stats["peak_bytes"], entry)
    )
    assert rules == {"UL202", "UL203"}


# ---------------------------------------------------------------------
# Pass 3: the serve engine's jits through Pass 1 + Pass 3 (no device
# execution)
# ---------------------------------------------------------------------

@pytest.mark.slow  # subprocess/compile latency; CI's full pytest runs it
def test_serve_jits_trace_clean_through_pass1_and_pass3(tmp_path):
    from unicore_tpu.analysis import hlo_audit
    from unicore_tpu.analysis.scenarios import build_demo_serve_engine
    from unicore_tpu.analysis.trace_audit import (
        audit_donation,
        audit_jaxpr,
    )

    engine = build_demo_serve_engine()
    # the ragged unification's whole point: the compile surface is a
    # CONSTANT two widths, independent of prompt length (the old
    # per-pow2-bucket family here was (8, 16, 32, 64, 128) + decode)
    assert engine.serve_step_widths() == (1, engine.prefill_chunk)
    assert hlo_audit.audit_serve_recompiles(
        engine.width_fn, engine.serve_step_widths(),
        engine.prefill_chunk,
    ) == []
    arts = engine.trace_step_fns()
    assert set(arts) == {"ragged-w1", f"ragged-w{engine.prefill_chunk}"}
    for name, art in arts.items():
        found = audit_jaxpr(art["jaxpr"], context=f"serve/{name}")
        found += audit_donation(art["lowered"], context=f"serve/{name}")
        assert found == [], (name,
                             "\n".join(f.render() for f in found))
        compiled = art["lowered"].compile()
        _, stats, _ = hlo_audit.audit_compiled(
            compiled, context=f"serve/{name}"
        )
        assert stats["peak_bytes"] is None or stats["peak_bytes"] > 0
    # a sabotaged width fn (one lowering per chunk size — the
    # recompile explosion) is caught statically before it can compile
    engine.width_fn = lambda m: max(m, 1)
    found = hlo_audit.audit_serve_recompiles(
        engine.width_fn, engine.serve_step_widths(),
        engine.prefill_chunk,
    )
    assert rules_of(found) == {"UL205"}


# ---------------------------------------------------------------------
# Pass 3 CLI contract: merged JSON schema, exit codes, budget
# round-trip through the real CLI (dp variant: the fastest compile)
# ---------------------------------------------------------------------

def _run_cli(args, timeout=420):
    return subprocess.run(
        [sys.executable, "-m", "unicore_tpu.analysis", "-q"] + args,
        cwd=_repo_root(), capture_output=True, text=True,
        timeout=timeout,
    )


@pytest.mark.slow  # three subprocess AOT compiles (~2 min) — CI's full
def test_cli_pass3_budget_roundtrip_and_schema(tmp_path):  # pytest runs it
    budget = str(tmp_path / "comms.json")
    report = str(tmp_path / "r1.json")
    base = ["--no-lint", "--no-trace", "--config", "examples/bert",
            "--cpu-devices", "8", "--pass3", "--pass3-variants", "dp",
            "--budget-file", budget]
    # 1) fresh budgets: --update-budgets writes and exits clean
    proc = _run_cli(base + ["--update-budgets", "--json", report])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    r = json.loads(open(report).read())
    assert set(r["counts"]) == {"new", "suppressed"}
    assert r["pass3"]["fingerprint"]
    scen = {s["scenario"]: s for s in r["pass3"]["scenarios"]}
    assert "bert/dp" in scen
    assert scen["bert/dp"]["collective_bytes"]["all-reduce"] > 0
    assert scen["bert/dp"]["peak_bytes"] > 0
    # 2) a committed budget exceeded by >5% fails the CLI
    data = json.loads(open(budget).read())
    fp = r["pass3"]["fingerprint"]
    entry = data["budgets"][fp]["bert/dp"]
    entry["collective_bytes"] = {
        k: int(v * 0.5) for k, v in entry["collective_bytes"].items()
    }
    entry["peak_bytes"] = int(entry["peak_bytes"] * 0.5)
    open(budget, "w").write(json.dumps(data))
    proc = _run_cli(base + ["--json", report])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rules = {f["rule"]
             for f in json.loads(open(report).read())["new_findings"]}
    assert {"UL202", "UL203"} <= rules
    # 3) --update-budgets accepts the change and the run passes again
    proc = _run_cli(base + ["--update-budgets"])
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow  # subprocess/compile latency; CI's full pytest runs it
def test_cli_check_baseline_flags_rot(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    rotten = tmp_path / "baseline.json"
    rotten.write_text(json.dumps({"version": 1, "suppressions": [{
        "rule": "UL104", "name": "blocking-fetch",
        "location": "gone.py", "message": "was fixed long ago",
        "fingerprint": "deadbeefdeadbeef",
    }]}))
    proc = subprocess.run(
        [sys.executable, "-m", "unicore_tpu.analysis", "--no-trace",
         "-q", "--lint-root", str(clean), "--baseline", str(rotten),
         "--check-baseline"],
        cwd=_repo_root(), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 1
    assert "stale" in proc.stdout
    # without --check-baseline the same rot passes silently
    proc = subprocess.run(
        [sys.executable, "-m", "unicore_tpu.analysis", "--no-trace",
         "-q", "--lint-root", str(clean), "--baseline", str(rotten)],
        cwd=_repo_root(), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0


# ---------------------------------------------------------------------
# satellite: dropout identity/full-drop quantization warning
# ---------------------------------------------------------------------

def test_dropout_warns_once_on_identity_quantization(caplog):
    import importlib

    dropout_mod = importlib.import_module("unicore_tpu.ops.dropout")

    dropout_mod._warned_rates.clear()
    x = jnp.ones((8,))
    rng = jax.random.PRNGKey(0)
    with caplog.at_level("WARNING", logger=dropout_mod.__name__):
        out = dropout_mod.dropout(x, 0.001, rng)  # quantizes to identity
        dropout_mod.dropout(x, 0.001, rng)        # second call: no new warn
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    warns = [r for r in caplog.records if "quantizes" in r.message]
    assert len(warns) == 1


def test_dropout_strict_raises_on_dead_rate():
    import importlib

    dropout_mod = importlib.import_module("unicore_tpu.ops.dropout")

    x = jnp.ones((8,))
    rng = jax.random.PRNGKey(0)
    with pytest.raises(ValueError, match="quantizes"):
        dropout_mod.dropout(x, 0.9995, rng, strict=True)
    # representable rates never warn or raise
    dropout_mod.dropout(x, 0.1, rng, strict=True)


def test_dropout_zero_and_one_rates_stay_silent(caplog):
    import importlib

    dropout_mod = importlib.import_module("unicore_tpu.ops.dropout")

    dropout_mod._warned_rates.clear()
    x = jnp.ones((8,))
    rng = jax.random.PRNGKey(0)
    with caplog.at_level("WARNING", logger=dropout_mod.__name__):
        dropout_mod.dropout(x, 0.0, rng)
        out = dropout_mod.dropout(x, 1.0, rng)
    np.testing.assert_array_equal(np.asarray(out), np.zeros_like(x))
    assert [r for r in caplog.records if "quantizes" in r.message] == []


# ---------------------------------------------------------------------
# UL201 zero1 certification (ISSUE 15): synthetic units + real compiles
# ---------------------------------------------------------------------

def test_ul201_zero1_unit_fires_and_stays_silent():
    from unicore_tpu.analysis.hlo_audit import audit_zero1_collectives

    mesh = _mesh()  # data=8
    params = {"w": jnp.zeros((64, 64), jnp.float32)}  # 16 KiB leaf
    data_slab = [range(8)]
    healthy = [
        _coll("all-reduce", 16384, data_slab),
        _coll("all-gather", 20000, data_slab),
    ]
    assert audit_zero1_collectives(mesh, healthy, params,
                                   context="t") == []
    # reduce-scatter proper (the TPU form) also satisfies the rule
    rs = [
        _coll("reduce-scatter", 2048, data_slab),
        _coll("all-gather", 20000, data_slab),
    ]
    assert audit_zero1_collectives(mesh, rs, params, context="t") == []
    # plain dp signature: data all-reduce but no param-sized gather
    dead = [_coll("all-reduce", 16384, data_slab)]
    found = audit_zero1_collectives(mesh, dead, params, context="t")
    assert rules_of(found) == {"UL201"}
    assert "zero1-disengaged" in found[0].name
    # no data reduction at all: both signatures missing
    none = [_coll("all-gather", 512, data_slab)]
    found = audit_zero1_collectives(mesh, none, params, context="t")
    assert len(found) == 2
    # a tensor-axis gather must not count toward the data signature
    mesh_tp = _mesh(tensor=2)  # data=4, tensor=2
    tp_pairs = [(0, 1), (2, 3), (4, 5), (6, 7)]  # vary along tensor
    tp_only = [
        _coll("all-reduce", 16384, [(0, 2, 4, 6), (1, 3, 5, 7)]),
        _coll("all-gather", 20000, tp_pairs),
    ]
    found = audit_zero1_collectives(mesh_tp, tp_only, params, context="t")
    assert rules_of(found) == {"UL201"}
    # 1-device data axis: --zero1 is a declared no-op, rule silent
    mesh_1 = jax.sharding.Mesh(
        np.asarray(jax.devices()[:8]).reshape(1, 8, 1, 1),
        ("data", "fsdp", "seq", "tensor"),
    )
    assert audit_zero1_collectives(mesh_1, dead, params, context="t") == []


@pytest.fixture(scope="module")
def zero1_compiled():
    import os

    from unicore_tpu.analysis.scenarios import (
        build_bert_scenario,
        restore_globals,
        snapshot_globals,
    )

    snap = snapshot_globals()
    try:
        trainer, samples, _ = build_bert_scenario(
            os.path.join(_repo_root(), "examples", "bert"),
            {"zero1": True, "optim_bf16_moments": True},
            jax.devices()[:8],
        )
        art = trainer.trace_train_step(samples)
        compiled = art["lowered"].compile()
        yield trainer, art, compiled
    finally:
        restore_globals(snap)


@pytest.mark.slow  # AOT-compiles the real step; CI's full pytest runs it
def test_ul201_zero1_silent_on_healthy_compile(zero1_compiled):
    """ISSUE 15 acceptance: the real --zero1 --optim-bf16-moments
    compile carries the sharded-update group signature (data-axis
    reduction + param-sized update all-gathers) and the certifier is
    silent; the moments really are data-sharded bf16."""
    from unicore_tpu.analysis import hlo_audit

    trainer, art, compiled = zero1_compiled
    colls = hlo_audit.extract_collectives(compiled.as_text(), 8)
    found = hlo_audit.audit_zero1_collectives(
        trainer.mesh, colls, art["state"]["params"], context="bert/zero1"
    )
    assert found == [], "\n".join(f.render() for f in found)
    for leaf in jax.tree_util.tree_leaves(
            trainer.state["opt_state"]["exp_avg"]):
        assert leaf.dtype == jnp.bfloat16
        if leaf.ndim >= 2:
            axes = {a for e in leaf.sharding.spec if e
                    for a in (e if isinstance(e, tuple) else (e,))}
            assert "data" in axes


@pytest.mark.slow  # AOT-compiles the real step; CI's full pytest runs it
def test_ul201_zero1_fires_on_disengaged_spec():
    """The disengaged fixture: a plain-dp compile (moments replicated)
    audited under a declared --zero1 must fire — the update gathers
    that prove per-replica sharding are absent."""
    import os

    from unicore_tpu.analysis import hlo_audit
    from unicore_tpu.analysis.scenarios import (
        build_bert_scenario,
        restore_globals,
        snapshot_globals,
    )

    snap = snapshot_globals()
    try:
        trainer, samples, _ = build_bert_scenario(
            os.path.join(_repo_root(), "examples", "bert"), {},
            jax.devices()[:8],
        )
        art = trainer.trace_train_step(samples)
        compiled = art["lowered"].compile()
        colls = hlo_audit.extract_collectives(compiled.as_text(), 8)
        found = hlo_audit.audit_zero1_collectives(
            trainer.mesh, colls, art["state"]["params"],
            context="bert/zero1-disengaged",
        )
        assert "UL201" in rules_of(found), found
        assert any("zero1-disengaged" in f.name for f in found)
    finally:
        restore_globals(snap)


def test_committed_zero1_budget_strictly_below_dp():
    """ISSUE 15 acceptance: the committed UL203 budget pins the zero1
    scenarios' peak HBM strictly below their replicated baselines for
    this environment's fingerprint."""
    import os

    from unicore_tpu.analysis import hlo_audit

    path = os.path.join(_repo_root(), "tools", "comms_baseline.json")
    budgets = hlo_audit.load_budgets(path)
    fp = hlo_audit.pass3_fingerprint()
    section = budgets.get("budgets", {}).get(fp)
    if not section or "bert/zero1" not in section:
        pytest.skip(f"no committed budgets for fingerprint {fp}")
    assert (section["bert/zero1"]["peak_bytes"]
            < section["bert/dp"]["peak_bytes"])
    assert (section["bert/zero1_tp2"]["peak_bytes"]
            < section["bert/tp2"]["peak_bytes"])


# ---------------------------------------------------------------------
# UL114 replicated-optim-state (ISSUE 15)
# ---------------------------------------------------------------------

def test_ul114_fires_on_bare_init_in_zero1_module(tmp_path):
    found = _lint_snippet(tmp_path, "tr.py", """
        import jax
        class T:
            def setup(self, args, params):
                self.zero1 = bool(args.zero1)
                self.opt_state = self.optimizer.init(params)
    """)
    assert "UL114" in rules_of(found)


def test_ul114_fires_on_init_allocations(tmp_path):
    found = _lint_snippet(tmp_path, "opt.py", """
        import jax
        import jax.numpy as jnp
        class Opt:
            def __init__(self, args):
                self.zero1 = args.zero1
            def init(self, params):
                return jax.tree_util.tree_map(jnp.zeros_like, params)
    """)
    assert "UL114" in rules_of(found)
    found = _lint_snippet(tmp_path, "opt2.py", """
        import jax
        import jax.numpy as jnp
        class Opt:
            def __init__(self, args):
                self.zero1 = args.zero1
            def init(self, params):
                zeros = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
                return jax.tree_util.tree_map(zeros, params)
    """)
    assert "UL114" in rules_of(found)


def test_ul114_silent_on_sanctioned_paths(tmp_path):
    # jit(init, out_shardings=...) — the Trainer._init_opt_state shape
    found = _lint_snippet(tmp_path, "ok1.py", """
        import jax
        class T:
            def setup(self, args, params, sh):
                self.zero1 = bool(args.zero1)
                self.opt_state = jax.jit(
                    self.optimizer.init, out_shardings=sh)(params)
    """)
    assert "UL114" not in rules_of(found)
    # result wrapped in a sharding constraint
    found = _lint_snippet(tmp_path, "ok2.py", """
        import jax
        class T:
            def setup(self, args, params, sh):
                self.zero1 = bool(args.zero1)
                self.opt_state = jax.lax.with_sharding_constraint(
                    self.optimizer.init(params), sh)
    """)
    assert "UL114" not in rules_of(found)
    # no zero1 plumbing: replicated moments are just the dp layout
    found = _lint_snippet(tmp_path, "ok3.py", """
        import jax
        import jax.numpy as jnp
        class Opt:
            def init(self, params):
                return jax.tree_util.tree_map(jnp.zeros_like, params)
        class T:
            def setup(self, params):
                self.opt_state = self.optimizer.init(params)
    """)
    assert "UL114" not in rules_of(found)


def test_ul114_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "sup.py", """
        import jax
        class T:
            def setup(self, args, params):
                self.zero1 = bool(args.zero1)
                self.opt_state = self.optimizer.init(params)  # unicore-lint: disable=UL114
    """)
    assert "UL114" not in rules_of(found)


def test_ul114_repo_sweep_clean():
    import os

    root = _repo_root()
    found = [
        f for f in lint_paths(
            [os.path.join(root, "unicore_tpu"),
             os.path.join(root, "bench.py"),
             os.path.join(root, "tools")],
            rel_to=root,
        )
        if f.rule == "UL114"
    ]
    assert found == [], "\n".join(f.render() for f in found)


# ---------------------------------------------------------------------
# Pass 4: compiled-schedule audit (UL301/UL302/UL303) —
# unicore_tpu/analysis/schedule_audit.py
# ---------------------------------------------------------------------

def _sched_module(body):
    """Synthetic scheduled-HLO module text in the exact dump format
    ``compiled.as_text()`` emits (two-space indent, ``%name = shape
    op(...)``) — the fixtures feed the SAME parser path a real
    compile's text does."""
    return (
        "HloModule fixture, is_scheduled=true\n\n"
        "ENTRY %main.1 (p0: f32[64,64]) -> f32[64,64] {\n"
        "  %p0 = f32[64,64]{1,0} parameter(0)\n"
        + body
        + "  ROOT %out.1 = f32[64,64]{1,0} add(f32[64,64]{1,0} %p0, "
          "f32[64,64]{1,0} %p0)\n}\n"
    )


_AG_START = (
    "  %ag-start = (f32[64,64]{1,0}, f32[128,64]{1,0}) "
    "all-gather-start(f32[64,64]{1,0} %p0), replica_groups={{0,1}}, "
    "dimensions={0}\n"
)
_AG_DONE = (
    "  %ag-done = f32[128,64]{1,0} all-gather-done((f32[64,64]{1,0}, "
    "f32[128,64]{1,0}) %ag-start)\n"
)
# 2 * 64*64 result elems * 128 contraction = 1048576 flops
_BIG_DOT = (
    "  %dot.1 = f32[64,64]{1,0} dot(f32[64,128]{1,0} %p0, "
    "f32[128,64]{1,0} %p0), lhs_contracting_dims={1}, "
    "rhs_contracting_dims={0}\n"
)


def test_schedule_parser_structure_and_pairs():
    from unicore_tpu.analysis import schedule_audit as sa

    comps = sa.parse_schedule(_sched_module(_AG_START + _BIG_DOT
                                            + _AG_DONE))
    assert len(comps) == 1 and comps[0].is_entry
    ops = [i.op for i in comps[0].instrs]
    assert ops == ["parameter", "all-gather-start", "dot",
                   "all-gather-done", "add"]
    pairs, unmatched, orphans, crossed = sa.match_async_pairs(comps[0])
    assert len(pairs) == 1 and not (unmatched or orphans or crossed)
    start, done = pairs[0]
    assert start.kind == "all-gather" and start.is_float
    # -start tuple result counts the LARGEST component only (the
    # operand alias must not double-count the transfer)
    assert start.bytes == 128 * 64 * 4


def test_schedule_parser_interleaved_pairs_match_by_operand():
    from unicore_tpu.analysis import schedule_audit as sa

    body = (
        _AG_START
        + "  %ar-start = f32[256]{0} all-reduce-start(f32[256]{0} %p0), "
          "replica_groups={{0,1}}, to_apply=%add\n"
        + _BIG_DOT
        + _AG_DONE
        + "  %ar-done = f32[256]{0} all-reduce-done(f32[256]{0} "
          "%ar-start)\n"
    )
    comps = sa.parse_schedule(_sched_module(body))
    pairs, unmatched, orphans, crossed = sa.match_async_pairs(comps[0])
    # healthy interleaving (s1 s2 d1 d2) pairs by OPERAND, not nesting
    assert {(s.name, d.name) for s, d in pairs} == {
        ("ag-start", "ag-done"), ("ar-start", "ar-done")}
    assert not (unmatched or orphans or crossed)
    found, stats = sa.audit_schedule_text(
        _sched_module(body), context="fix")
    assert [f for f in found if f.rule == "UL303"] == []
    assert stats["async_pairs"] == 2


def test_schedule_window_attribution_counts_dot_flops():
    from unicore_tpu.analysis import schedule_audit as sa

    _, stats = sa.audit_schedule_text(
        _sched_module(_AG_START + _BIG_DOT + _AG_DONE), context="fix")
    assert stats["window_flops"] == 2 * 64 * 64 * 128
    assert stats["async_collectives"] == 1
    assert stats["overlap_ratio"] == 1.0
    assert stats["exposed_collective_bytes"] == 0


def test_ul303_unmatched_start_and_orphan_done():
    from unicore_tpu.analysis import schedule_audit as sa

    found, _ = sa.audit_schedule_text(
        _sched_module(_AG_START + _BIG_DOT), context="fix")
    msgs = [f for f in found if f.rule == "UL303"]
    assert msgs and "no matching -done" in msgs[0].message

    found, _ = sa.audit_schedule_text(
        _sched_module(_BIG_DOT + _AG_DONE), context="fix")
    msgs = [f for f in found if f.rule == "UL303"]
    assert msgs and "no known -start" in msgs[0].message


def test_ul303_crossed_pair_is_corruption():
    from unicore_tpu.analysis import schedule_audit as sa

    found, _ = sa.audit_schedule_text(
        _sched_module(_AG_DONE + _BIG_DOT + _AG_START), context="fix")
    msgs = [f.message for f in found if f.rule == "UL303"]
    assert any("BEFORE its start" in m for m in msgs), found


def test_ul303_zero_width_window_warns():
    from unicore_tpu.analysis import schedule_audit as sa

    found, stats = sa.audit_schedule_text(
        _sched_module(_AG_START + _AG_DONE + _BIG_DOT), context="fix")
    assert stats["zero_width_pairs"] == 1
    assert any(f.rule == "UL303" and f.severity == "warning"
               for f in found)


def test_ul301_fires_on_serialized_schedule():
    """The deliberately serialized fixture: an empty start/done window
    with overlappable compute scheduled after it must fire UL301."""
    from unicore_tpu.analysis import schedule_audit as sa

    found, stats = sa.audit_schedule_text(
        _sched_module(_AG_START + _AG_DONE + _BIG_DOT), context="fix")
    fired = [f for f in found if f.rule == "UL301"]
    assert fired and "exposed" in fired[0].message
    assert stats["overlap_ratio"] == 0.0
    assert stats["exposed_collective_bytes"] == 128 * 64 * 4


def test_ul301_silent_when_overlapped():
    from unicore_tpu.analysis import schedule_audit as sa

    found, _ = sa.audit_schedule_text(
        _sched_module(_AG_START + _BIG_DOT + _AG_DONE), context="fix")
    assert [f for f in found if f.rule == "UL301"] == []


def test_ul301_whitelists_tail_positioned_collective():
    """Nothing above the compute floor after the done: there is no
    compute left to hide the collective behind — silent."""
    from unicore_tpu.analysis import schedule_audit as sa

    found, _ = sa.audit_schedule_text(
        _sched_module(_BIG_DOT + _AG_START + _AG_DONE), context="fix")
    assert [f for f in found if f.rule == "UL301"] == []


def test_ul301_whitelists_op_name_patterns():
    from unicore_tpu.analysis import schedule_audit as sa

    wl_start = _AG_START.replace(
        "dimensions={0}\n",
        'dimensions={0}, metadata={op_name="zero1_param_gather"}\n')
    found, _ = sa.audit_schedule_text(
        _sched_module(wl_start + _AG_DONE + _BIG_DOT), context="fix")
    assert [f for f in found if f.rule == "UL301"] == []


def test_ul301_ignores_int_collectives():
    from unicore_tpu.analysis import schedule_audit as sa

    body = (
        "  %rng-start = (u32[64]{0}, u32[128]{0}) all-gather-start("
        "u32[64]{0} %p0), replica_groups={{0,1}}, dimensions={0}\n"
        "  %rng-done = u32[128]{0} all-gather-done((u32[64]{0}, "
        "u32[128]{0}) %rng-start)\n" + _BIG_DOT
    )
    found, _ = sa.audit_schedule_text(_sched_module(body), context="fix")
    assert [f for f in found if f.rule == "UL301"] == []


def test_sync_collectives_count_as_exposed():
    """XLA:CPU lowers every collective synchronously — no async pairs;
    every byte exposed by construction (the documented CPU caveat)."""
    from unicore_tpu.analysis import schedule_audit as sa

    body = (
        "  %ar.1 = f32[256]{0} all-reduce(f32[256]{0} %p0), "
        "replica_groups={{0,1}}, to_apply=%add\n" + _BIG_DOT
    )
    found, stats = sa.audit_schedule_text(
        _sched_module(body), context="fix")
    assert found == []
    assert stats["sync_collectives"] == 1
    assert stats["async_pairs"] == 0
    assert stats["overlap_ratio"] == 0.0
    assert stats["exposed_collective_bytes"] == 256 * 4
    assert stats["exposed_collective_bytes"] == \
        stats["total_collective_bytes"]


def test_ul302_budget_semantics(tmp_path):
    from unicore_tpu.analysis import hlo_audit
    from unicore_tpu.analysis import schedule_audit as sa

    stats = {"total_collective_bytes": 1000,
             "overlapped_collective_bytes": 600,
             "exposed_collective_bytes": 400, "overlap_ratio": 0.6}
    # no committed entry -> warning nudge toward --update-budgets
    got = sa.audit_overlap_budget("bert/dp", stats, None)
    assert [f.severity for f in got] == ["warning"]
    # matching entry -> clean
    entry = {"exposed_collective_bytes": 400, "overlap_ratio": 0.6}
    assert sa.audit_overlap_budget("bert/dp", stats, entry) == []
    # exposed bytes regressed >5% -> error
    got = sa.audit_overlap_budget(
        "bert/dp", stats, {"exposed_collective_bytes": 300,
                           "overlap_ratio": 0.6})
    assert [f.rule for f in got] == ["UL302"]
    assert got[0].severity == "error"
    # overlap ratio regressed >5% -> error
    got = sa.audit_overlap_budget(
        "bert/dp", stats, {"exposed_collective_bytes": 400,
                           "overlap_ratio": 0.8})
    assert [f.rule for f in got] == ["UL302"]
    # budgeted fully-overlapped: ANY exposure fires
    got = sa.audit_overlap_budget(
        "bert/dp", stats, {"exposed_collective_bytes": 0,
                           "overlap_ratio": 1.0})
    assert {f.rule for f in got} == {"UL302"}
    # a scenario with no collectives has nothing to budget
    assert sa.audit_overlap_budget(
        "serve/ragged-w1", {"total_collective_bytes": 0}, None) == []


def test_budget_entries_merge_across_passes(tmp_path):
    """Pass-3 and Pass-4 keys share one scenario entry: refreshing
    either pass must not erase the other's keys."""
    from unicore_tpu.analysis import hlo_audit
    from unicore_tpu.analysis import schedule_audit as sa

    path = str(tmp_path / "comms.json")
    fp = "fmtX|test|n8|jax0"
    hlo_audit.update_budget_entries(path, fp, {"bert/dp": {
        "collective_bytes": {"all-reduce": 123}, "peak_bytes": 456}})
    sa.update_schedule_budget_entries(path, fp, {"bert/dp": {
        "overlap_ratio": 0.5, "exposed_collective_bytes": 789}})
    entry = hlo_audit.budget_entry(hlo_audit.load_budgets(path), fp,
                                   "bert/dp")
    assert entry == {"collective_bytes": {"all-reduce": 123},
                     "peak_bytes": 456, "overlap_ratio": 0.5,
                     "exposed_collective_bytes": 789}
    # pass3 refresh keeps pass4 keys; pass4 refresh keeps pass3 keys
    hlo_audit.update_budget_entries(path, fp, {"bert/dp": {
        "collective_bytes": {"all-reduce": 200}, "peak_bytes": 500}})
    sa.update_schedule_budget_entries(path, fp, {"bert/dp": {
        "overlap_ratio": 0.25, "exposed_collective_bytes": 1000}})
    entry = hlo_audit.budget_entry(hlo_audit.load_budgets(path), fp,
                                   "bert/dp")
    assert entry == {"collective_bytes": {"all-reduce": 200},
                     "peak_bytes": 500, "overlap_ratio": 0.25,
                     "exposed_collective_bytes": 1000}


def test_schedule_audit_deterministic_on_same_text():
    from unicore_tpu.analysis import schedule_audit as sa

    text = _sched_module(_AG_START + _AG_DONE + _BIG_DOT)
    f1, s1 = sa.audit_schedule_text(text, context="fix")
    f2, s2 = sa.audit_schedule_text(text, context="fix")
    assert s1 == s2
    assert [f.fingerprint for f in f1] == [f.fingerprint for f in f2]


@pytest.mark.slow  # AOT-compiles the real step; CI's full pytest runs it
def test_pass4_silent_on_healthy_zero1_compile(zero1_compiled):
    """Acceptance: the healthy real compile is UL301/UL303-silent, and
    its stats carry the documented CPU shape — sync collectives only,
    every byte exposed (the before-number the item-5 overlap campaign
    commits to push down)."""
    from unicore_tpu.analysis import schedule_audit as sa

    _, _, compiled = zero1_compiled
    found, stats = sa.audit_compiled_schedule(compiled,
                                              context="bert/zero1")
    assert found == [], "\n".join(f.render() for f in found)
    assert stats["sync_collectives"] > 0
    assert stats["async_pairs"] == 0
    assert stats["overlap_ratio"] == 0.0
    assert stats["total_collective_bytes"] > 0
    assert stats["exposed_collective_bytes"] == \
        stats["total_collective_bytes"]


@pytest.mark.slow  # AOT-compiles the real step; CI's full pytest runs it
def test_pass4_byte_totals_match_pass3(zero1_compiled):
    """The two passes count the same collectives: Pass 4's total bytes
    must equal the sum of Pass 3's per-kind byte budget."""
    from unicore_tpu.analysis import hlo_audit
    from unicore_tpu.analysis import schedule_audit as sa

    _, _, compiled = zero1_compiled
    text = compiled.as_text()
    colls = hlo_audit.extract_collectives(text, 8)
    _, stats = sa.audit_schedule_text(text, context="bert/zero1")
    assert stats["total_collective_bytes"] == sum(c.bytes for c in colls)


@pytest.mark.slow  # three subprocess AOT compiles (~2 min) — CI's full
def test_cli_pass4_budget_roundtrip_and_schema(tmp_path):  # pytest runs it
    budget = str(tmp_path / "comms.json")
    report = str(tmp_path / "r1.json")
    base = ["--no-lint", "--no-trace", "--config", "examples/bert",
            "--cpu-devices", "8", "--pass4", "--pass3-variants", "dp",
            "--budget-file", budget]
    # 1) fresh budgets: --update-budgets writes and exits clean
    proc = _run_cli(base + ["--update-budgets", "--json", report])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    r = json.loads(open(report).read())
    assert r["pass4"]["fingerprint"]
    assert "pass3" not in r  # --pass4 alone reports pass 4 only
    scen = {s["scenario"]: s for s in r["pass4"]["scenarios"]}
    assert scen["bert/dp"]["overlap_ratio"] == 0.0  # CPU: all exposed
    assert scen["bert/dp"]["exposed_collective_bytes"] > 0
    assert scen["bert/dp"]["sync_collectives"] > 0
    data = json.loads(open(budget).read())
    entry = data["budgets"][r["pass4"]["fingerprint"]]["bert/dp"]
    assert set(entry) == {"overlap_ratio", "exposed_collective_bytes"}
    # 2) a tightened budget (claims less exposure than reality) fails
    entry["exposed_collective_bytes"] = int(
        entry["exposed_collective_bytes"] * 0.5)
    open(budget, "w").write(json.dumps(data))
    proc = _run_cli(base + ["--json", report])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rules = {f["rule"]
             for f in json.loads(open(report).read())["new_findings"]}
    assert rules == {"UL302"}
    # 3) --update-budgets accepts the measurement; clean again
    proc = _run_cli(base + ["--update-budgets"])
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------
# Budget-scenario rot surface (--check-baseline over comms_baseline)
# ---------------------------------------------------------------------

def test_known_budget_scenarios_cover_committed_file():
    import os

    from unicore_tpu.analysis.scenarios import (
        known_budget_scenarios,
        stale_budget_scenarios,
    )

    known = known_budget_scenarios()
    assert "bert/zero1" in known and "bert/fsdp2-uf1" in known
    assert any(s.startswith("serve/ragged-w") for s in known)
    committed = os.path.join(_repo_root(), "tools",
                             "comms_baseline.json")
    assert stale_budget_scenarios(committed) == []


def test_stale_budget_scenarios_flags_rot(tmp_path):
    from unicore_tpu.analysis.scenarios import stale_budget_scenarios

    path = str(tmp_path / "comms.json")
    with open(path, "w") as fh:
        json.dump({"version": 1, "budgets": {
            "fp-a": {"bert/dp": {}, "serve/prefill-b8": {}},
            "fp-b": {"bert/gone2": {}},
        }}, fh)
    assert stale_budget_scenarios(path) == [
        ("fp-a", "serve/prefill-b8"), ("fp-b", "bert/gone2")]
    # absent file: nothing to check
    assert stale_budget_scenarios(str(tmp_path / "nope.json")) == []


@pytest.mark.slow  # subprocess + serve-engine build; CI runs it
def test_cli_check_baseline_flags_budget_rot(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    rotten = tmp_path / "comms.json"
    rotten.write_text(json.dumps({"version": 1, "budgets": {
        "fmt1|cpu|n8|jax0.4.37": {"serve/prefill-b8": {
            "peak_bytes": 1}}}}))
    base = [sys.executable, "-m", "unicore_tpu.analysis", "--no-trace",
            "-q", "--lint-root", str(clean), "--no-baseline",
            "--budget-file", str(rotten)]
    proc = subprocess.run(
        base + ["--check-baseline"], cwd=_repo_root(),
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale budget scenario" in proc.stdout
    # without --check-baseline the same rot passes silently
    proc = subprocess.run(
        base, cwd=_repo_root(), capture_output=True, text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------
# UL115 — unjoined daemon thread
# ---------------------------------------------------------------------

def test_ul115_fires_on_unstopped_daemon_worker(tmp_path):
    found = _lint_snippet(tmp_path, "w.py", """
        import threading
        class Worker:
            def go(self):
                self._thread = threading.Thread(
                    target=self._run, daemon=True)
                self._thread.start()
    """)
    assert "UL115" in rules_of(found)


def test_ul115_fires_on_chained_fire_and_forget(tmp_path):
    found = _lint_snippet(tmp_path, "w.py", """
        import threading
        def kick(fn):
            threading.Thread(target=fn, daemon=True).start()
    """)
    fired = [f for f in found if f.rule == "UL115"]
    assert fired and "drops the only reference" in fired[0].message


def test_ul115_silent_with_shutdown_method(tmp_path):
    # the watchdog shape: close() stops the worker with a flag, no join
    found = _lint_snippet(tmp_path, "w.py", """
        import threading
        class Worker:
            def go(self):
                self._thread = threading.Thread(
                    target=self._run, daemon=True)
                self._thread.start()
            def close(self):
                self._stop = True
    """)
    assert "UL115" not in rules_of(found)


def test_ul115_silent_with_join(tmp_path):
    found = _lint_snippet(tmp_path, "w.py", """
        from threading import Thread
        def run_briefly(fn):
            t = Thread(target=fn, daemon=True)
            t.start()
            t.join(timeout=1.0)
    """)
    assert "UL115" not in rules_of(found)


def test_ul115_silent_on_non_daemon_thread(tmp_path):
    # a non-daemon thread blocks exit visibly instead of losing work
    found = _lint_snippet(tmp_path, "w.py", """
        import threading
        def go(fn):
            t = threading.Thread(target=fn)
            t.start()
    """)
    assert "UL115" not in rules_of(found)


def test_ul115_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "w.py", """
        import threading
        def kick(fn):
            threading.Thread(target=fn, daemon=True).start()  # unicore-lint: disable=UL115
    """)
    assert "UL115" not in rules_of(found)


def test_ul115_repo_sweep_clean():
    """async_writer, prefetch pump, watchdog, and the fleet router are
    the intended-clean worker spawns — each owns a stop/close/drain
    shutdown path."""
    import os

    root = _repo_root()
    found = [
        f for f in lint_paths(
            [os.path.join(root, "unicore_tpu"),
             os.path.join(root, "bench.py"),
             os.path.join(root, "tools")],
            rel_to=root,
        )
        if f.rule == "UL115"
    ]
    assert found == [], "\n".join(f.render() for f in found)


# ---------------------------------------------------------------------
# UL116 unverified-checkpoint-read
# ---------------------------------------------------------------------

def _lint_deploy_snippet(tmp_path, code, name="sub.py"):
    """Write the snippet under a deploy/ dir so the UL116 path
    predicate (deploy/serve/fleet code) marks it in scope."""
    d = tmp_path / "deploy"
    d.mkdir(exist_ok=True)
    f = d / name
    f.write_text(textwrap.dedent(code))
    return lint_paths([str(f)])


def test_ul116_fires_on_raw_checkpoint_reads(tmp_path):
    # open(manifest_path), pickle.loads(ckpt_bytes), and both halves of
    # pickle.load(open("checkpoint_last.pt")) are raw checkpoint reads
    # with neither read_verified nor a typed re-raise around them
    found = _lint_deploy_snippet(tmp_path, """
        import pickle
        def read_manifest(manifest_path):
            with open(manifest_path, "rb") as fh:
                return pickle.loads(fh.read())
        def from_bytes(ckpt_bytes):
            return pickle.loads(ckpt_bytes)
        def from_literal():
            return pickle.load(open("checkpoint_last.pt", "rb"))
    """)
    ul116 = [f for f in found if f.rule == "UL116"]
    assert len(ul116) >= 3, found


def test_ul116_silent_on_read_verified_and_typed_reraise(tmp_path):
    # the two sanctioned shapes — bytes straight out of read_verified,
    # or a try whose handler re-raises typed — plus a read that never
    # names checkpoint bytes at all
    found = _lint_deploy_snippet(tmp_path, """
        import pickle
        from unicore_tpu.checkpoint_utils import (CheckpointIntegrityError,
                                                  read_verified)
        def read_manifest(manifest_path):
            return pickle.loads(read_verified(manifest_path))
        def read_guarded(ckpt_path):
            try:
                with open(ckpt_path, "rb") as fh:
                    return pickle.loads(fh.read())
            except OSError as e:
                raise CheckpointIntegrityError(str(e)) from e
        def read_prompts(prompts_path):
            with open(prompts_path) as fh:
                return fh.read()
    """)
    assert "UL116" not in rules_of(found)


def test_ul116_try_does_not_guard_nested_def(tmp_path):
    # a function DEFINED inside a re-raising try executes later,
    # outside the guard — its raw read still fires
    found = _lint_deploy_snippet(tmp_path, """
        import pickle
        def make_loader(manifest_path):
            try:
                def load():
                    return pickle.load(open(manifest_path, "rb"))
            except Exception as e:
                raise RuntimeError("never guards load()") from e
            return load
    """)
    assert "UL116" in rules_of(found)


def test_ul116_ignores_train_side_files(tmp_path):
    found = _lint_snippet(tmp_path, "train_utils.py", """
        import pickle
        def peek(ckpt_path):
            return pickle.load(open(ckpt_path, "rb"))
    """)
    assert "UL116" not in rules_of(found)


def test_ul116_inline_suppression(tmp_path):
    found = _lint_deploy_snippet(tmp_path, """
        import pickle
        def peek(ckpt_path):
            return pickle.load(open(ckpt_path, "rb"))  # unicore-lint: disable=UL116
    """)
    assert "UL116" not in rules_of(found)


def test_ul116_repo_sweep_clean():
    """Every checkpoint/manifest read in deploy/serve/fleet code goes
    through read_verified (deploy/loader.py, deploy/publish.py) or a
    typed re-raise."""
    import os

    root = _repo_root()
    found = [
        f for f in lint_paths(
            [os.path.join(root, "unicore_tpu"),
             os.path.join(root, "bench.py"),
             os.path.join(root, "tools")],
            rel_to=root,
        )
        if f.rule == "UL116"
    ]
    assert found == [], "\n".join(f.render() for f in found)


# ---------------------------------------------------------------------
# UL118 unbounded-replica-growth (elastic fleet satellite)
# ---------------------------------------------------------------------

def test_ul118_fires_on_unbounded_boot_shapes(tmp_path):
    # pressure-retry while loop appending fresh engines: no bound
    found = _lint_snippet(tmp_path, "grow1.py", """
        def grow(factory, engines, pressure):
            while pressure():
                engines.append(factory(len(engines)))
    """)
    assert "UL118" in rules_of(found)
    # subscript store keyed by a counter, not the loop variable
    found = _lint_snippet(tmp_path, "grow2.py", """
        def grow(factory, engines, events):
            n = 0
            for ev in events:
                if ev.hot:
                    n = n + 1
                    engines["a%d" % n] = factory(n)
    """)
    assert "UL118" in rules_of(found)
    # the boot laundered through a name before joining the fleet
    found = _lint_snippet(tmp_path, "grow3.py", """
        def grow(engine_factory, fleet, ticks):
            for t in ticks:
                eng = engine_factory(t.rid)
                fleet.add(eng)
    """)
    assert "UL118" in rules_of(found)


def test_ul118_silent_on_replacement_and_scale_gates(tmp_path):
    # rolling restart's replacement shape: same slot, no growth
    found = _lint_snippet(tmp_path, "roll.py", """
        def roll(factory, engines):
            for rid in sorted(engines):
                engines[rid] = factory(rid)
    """)
    assert "UL118" not in rules_of(found)
    # max-replicas bound in the loop
    found = _lint_snippet(tmp_path, "gated1.py", """
        def grow(factory, engines, pressure, max_replicas):
            while pressure():
                if len(engines) >= max_replicas:
                    break
                engines.append(factory(len(engines)))
    """)
    assert "UL118" not in rules_of(found)
    # a len() bound is a bound even when the cap name says nothing
    found = _lint_snippet(tmp_path, "gated1b.py", """
        def grow(factory, fleet, cap):
            while len(fleet) < cap:
                fleet.append(factory("r"))
    """)
    assert "UL118" not in rules_of(found)
    # cooldown gate in the loop
    found = _lint_snippet(tmp_path, "gated2.py", """
        def grow(factory, engines, pressure, cooldown_ok):
            while pressure():
                if not cooldown_ok():
                    continue
                engines.append(factory(len(engines)))
    """)
    assert "UL118" not in rules_of(found)
    # breaker-gated canary boot
    found = _lint_snippet(tmp_path, "gated3.py", """
        def grow(factory, engines, pressure, breaker):
            while pressure():
                if breaker.ready(0):
                    engines.append(factory(len(engines)))
    """)
    assert "UL118" not in rules_of(found)
    # a factory result that never joins a collection is a local probe
    found = _lint_snippet(tmp_path, "probe.py", """
        def probe(factory, ticks):
            for t in ticks:
                eng = factory(t)
                eng.close()
    """)
    assert "UL118" not in rules_of(found)


def test_ul118_inline_suppression(tmp_path):
    found = _lint_snippet(tmp_path, "sup.py", """
        def grow(factory, engines, pressure):
            while pressure():
                engines.append(factory(len(engines)))  # unicore-lint: disable=UL118
    """)
    assert "UL118" not in rules_of(found)


def test_ul118_repo_sweep_clean():
    """Every replica boot in the repo is gated — the autoscaler's
    envelope (max_replicas + cooldown + boot budget) and the router's
    breaker-gated canary keep fleet growth bounded."""
    import os

    root = _repo_root()
    found = [
        f for f in lint_paths(
            [os.path.join(root, "unicore_tpu"),
             os.path.join(root, "bench.py"),
             os.path.join(root, "tools")],
            rel_to=root,
        )
        if f.rule == "UL118"
    ]
    assert found == [], "\n".join(f.render() for f in found)
