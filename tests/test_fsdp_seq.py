"""Mesh-axis semantics: --fsdp-size shards optimizer/master state (ZeRO)
and --seq-parallel-size routes attention through ring/Ulysses — both must
produce the same update as pure data parallelism (VERDICT r1 item 4)."""

from argparse import Namespace

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu import metrics
from unicore_tpu.distributed import utils as dist_utils
from unicore_tpu.losses.unicore_loss import UnicoreLoss
from unicore_tpu.models.unicore_model import BaseUnicoreModel
from unicore_tpu.modules import SelfMultiheadAttention
from unicore_tpu.tasks.unicore_task import UnicoreTask
from unicore_tpu.trainer import Trainer

VOCAB, DIM, HEADS, SEQ = 16, 32, 4, 8


class AttnModel(BaseUnicoreModel):
    @nn.compact
    def __call__(self, src_tokens, deterministic=True, **kwargs):
        x = nn.Embed(VOCAB, DIM, name="embed")(src_tokens)
        x = x + SelfMultiheadAttention(
            embed_dim=DIM, num_heads=HEADS, dropout=0.0, name="attn"
        )(x, deterministic=deterministic)
        return nn.Dense(VOCAB, name="out")(x)


class LMLoss(UnicoreLoss):
    def forward(self, model, params, sample, rng=None, is_training=True):
        logits = model.apply(
            {"params": params}, **sample["net_input"],
            deterministic=not is_training,
        )
        lprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        target = sample["target"]
        nll = -jnp.take_along_axis(lprobs, target[..., None], axis=-1)[..., 0]
        loss = jnp.sum(nll)
        n = jnp.asarray(np.prod(target.shape), dtype=jnp.float32)
        return loss, n, {"loss": loss, "sample_size": n}

    @staticmethod
    def reduce_metrics(logging_outputs, split="train"):
        loss = sum(float(l.get("loss", 0)) for l in logging_outputs)
        n = sum(float(l.get("sample_size", 0)) for l in logging_outputs)
        metrics.log_scalar("loss", loss / max(n, 1), n, round=3)

    @staticmethod
    def logging_outputs_can_be_summed(is_train):
        return True


class _Task(UnicoreTask):
    pass


def make_args(**over):
    d = dict(
        seed=1, update_freq=[1], clip_norm=0.0, ema_decay=-1.0,
        fp16=False, bf16=False, bf16_sr=False,
        optimizer="adam", lr=[1e-2], adam_betas="(0.9, 0.999)",
        adam_eps=1e-8, weight_decay=0.0,
        lr_scheduler="fixed", force_anneal=None, lr_shrink=0.1,
        warmup_updates=0, min_loss_scale=1e-4, fp16_scale_window=None,
        fp16_init_scale=4.0, max_update=100, max_epoch=0,
        tensor_parallel_size=1, seq_parallel_size=1, fsdp_size=1,
    )
    d.update(over)
    return Namespace(**d)


def make_batch(rng, bsz=8):
    toks = rng.randint(0, VOCAB, size=(bsz, SEQ)).astype(np.int64)
    return {"net_input": {"src_tokens": toks}, "target": toks.copy()}


def run_one_step(batch, n_steps=1, **over):
    """Fresh mesh + trainer; returns params after n_steps updates."""
    dist_utils.reset_mesh()
    args = make_args(**over)
    task = _Task(args)
    trainer = Trainer(args, task, AttnModel(), LMLoss(task))
    metrics.reset()
    with metrics.aggregate("train"):
        for _ in range(n_steps):
            trainer.train_step([batch])
    return trainer


def _assert_params_close(t1, t2, atol):
    p1 = jax.device_get(t1.state["params"])
    p2 = jax.device_get(t2.state["params"])
    for a, b in zip(
        jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol)


@pytest.fixture(autouse=True)
def need_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    yield
    dist_utils.reset_mesh()
    from unicore_tpu import parallel

    parallel.disable_sequence_parallel()
    parallel.disable_tensor_parallel()


def _run_on_current_mesh(batch, **over):
    """Like run_one_step but keeps the pre-installed (restricted) mesh."""
    args = make_args(**over)
    task = _Task(args)
    trainer = Trainer(args, task, AttnModel(), LMLoss(task))
    metrics.reset()
    with metrics.aggregate("train"):
        trainer.train_step([batch])
    return trainer


def test_one_device_vs_eight_device_update(rng):
    """The real SPMD invariant: an 8-way sharded step computes the same
    update as the identical step on a single device."""
    batch = make_batch(rng, bsz=16)
    dist_utils.reset_mesh(
        dist_utils.get_mesh(None, devices=jax.devices()[:1])
    )
    t1 = _run_on_current_mesh(batch)
    dist_utils.reset_mesh()
    t8 = run_one_step(batch)
    _assert_params_close(t1, t8, atol=1e-6)


def test_fsdp_matches_pure_dp(rng):
    batch = make_batch(rng, bsz=16)
    t_dp = run_one_step(batch, n_steps=2)
    t_fsdp = run_one_step(batch, n_steps=2, fsdp_size=2)
    _assert_params_close(t_dp, t_fsdp, atol=1e-6)


def test_fsdp_actually_shards_state(rng):
    """Under --fsdp-size the optimizer/master state must be sharded, not
    replicated (the ZeRO promise of the axis name)."""
    batch = make_batch(rng, bsz=16)
    t = run_one_step(batch, fsdp_size=2)
    sharded = 0
    for leaf in jax.tree_util.tree_leaves(t.state["opt_state"]):
        if leaf.ndim >= 1 and not leaf.sharding.is_fully_replicated:
            shard = leaf.addressable_shards[0].data
            assert shard.size < leaf.size  # a true shard, not a replica
            sharded += 1
    assert sharded > 0, "no optimizer-state leaf is sharded over fsdp"
    for leaf in jax.tree_util.tree_leaves(t.state["params"]):
        if leaf.ndim >= 1 and max(leaf.shape) % 2 == 0:
            assert not leaf.sharding.is_fully_replicated
            break


def test_tp_matches_pure_dp(rng):
    """--tensor-parallel-size 2 must compute the same update as pure DP
    (VERDICT r3 missing-1: the tensor axis used to be dead — parsed but
    sharding nothing, silently duplicating work)."""
    batch = make_batch(rng, bsz=16)
    t_dp = run_one_step(batch, n_steps=2)
    t_tp = run_one_step(batch, n_steps=2, tensor_parallel_size=2)
    _assert_params_close(t_dp, t_tp, atol=1e-6)


def test_tp_actually_shards_params(rng):
    """Attention QKV/out-proj weights (and their Adam moments) must be
    sharded over the tensor axis, not replicated."""
    batch = make_batch(rng, bsz=16)
    t = run_one_step(batch, tensor_parallel_size=2)
    p = t.state["params"]["attn"]
    for name, leaf in (
        ("in_proj.kernel", p["in_proj"]["kernel"]),   # [D, 3, H, Dh] on H
        ("out_proj.kernel", p["out_proj"]["kernel"]),  # [D, D] on dim 0
    ):
        assert not leaf.sharding.is_fully_replicated, name
        shard = leaf.addressable_shards[0].data
        assert shard.size < leaf.size, name
    m = t.state["opt_state"]["exp_avg"]["attn"]["in_proj"]["kernel"]
    assert not m.sharding.is_fully_replicated


def test_tp_shards_vocab_embedding(rng):
    """The embedding table must shard its VOCAB dim over tensor (Megatron
    vocab-parallel; VERDICT r4 missing-2: TP used to skip the biggest
    matrices in the model — embedding + tied LM head)."""
    batch = make_batch(rng, bsz=16)
    t = run_one_step(batch, tensor_parallel_size=2)
    emb = t.state["params"]["embed"]["embedding"]
    assert not emb.sharding.is_fully_replicated
    shard = emb.addressable_shards[0].data
    assert shard.shape == (VOCAB // 2, DIM), shard.shape


def test_tp_fsdp_stacks_vocab_dim(rng):
    """Under tensor x fsdp both axes stack on the vocab dim (fsdp on the
    feature dim would force SPMD involuntary full-remats on the lookup)."""
    batch = make_batch(rng, bsz=16)
    t = run_one_step(batch, tensor_parallel_size=2, fsdp_size=2)
    emb = t.state["params"]["embed"]["embedding"]
    shard = emb.addressable_shards[0].data
    assert shard.shape == (VOCAB // 4, DIM), shard.shape


def test_tp_with_fsdp_matches_pure_dp(rng):
    """2D sharding: tensor x fsdp together must still match pure DP."""
    batch = make_batch(rng, bsz=16)
    t_dp = run_one_step(batch, n_steps=2)
    t_2d = run_one_step(
        batch, n_steps=2, tensor_parallel_size=2, fsdp_size=2
    )
    _assert_params_close(t_dp, t_2d, atol=1e-6)


def test_sharded_checkpoint_roundtrip(rng, tmp_path):
    """Under --fsdp-size the checkpoint is SHARDED: the main file holds
    ShardedLeaf markers, the data lives in .shard<p> files, and restore
    rebuilds per-device without assembling full arrays (VERDICT r3 weak-6
    / next-3).  A topology change (fsdp=2 ckpt into pure DP) falls back
    to full assembly from all shard files."""
    import os
    import pickle

    from unicore_tpu.checkpoint_utils import ShardedLeaf
    from unicore_tpu.trainer import Trainer

    batch = make_batch(rng, bsz=16)
    t1 = run_one_step(batch, n_steps=2, fsdp_size=2)
    fn = str(tmp_path / "ck.pt")
    t1.save_checkpoint(fn, {"train_iterator": {"epoch": 1}})
    assert os.path.exists(fn + ".shard0")
    with open(fn, "rb") as f:
        main = pickle.load(f)
    markers = [
        l for l in jax.tree_util.tree_leaves(
            main["model"], is_leaf=lambda x: isinstance(x, ShardedLeaf)
        ) if isinstance(l, ShardedLeaf)
    ]
    assert markers, "no sharded leaves recorded in the main file"

    def load_into(**over):
        dist_utils.reset_mesh()
        args = make_args(**over)
        task = _Task(args)
        t = Trainer(args, task, AttnModel(), LMLoss(task))
        t.load_checkpoint(fn)
        t.init_state(batch)
        return t

    # plant a STALE shard file (wrong token, garbage data): restore must
    # reject it instead of silently merging old weights in
    with open(fn + ".shard0", "rb") as f:
        payload = pickle.load(f)
    stale = {
        "process_index": 7,
        "token": "stale-run:999",
        "entries": {
            k: [(idx, np.full_like(piece, 1e6)) for idx, piece in v]
            for k, v in payload["entries"].items()
        },
    }
    with open(fn + ".shard7", "wb") as f:
        pickle.dump(stale, f)

    t2 = load_into(fsdp_size=2)  # same topology: per-shard fast path
    _assert_params_close(t1, t2, atol=0)
    t3 = load_into()  # pure DP: cross-topology full-assembly fallback
    _assert_params_close(t1, t3, atol=0)
    # the restored sharded trainer keeps training identically
    metrics.reset()
    with metrics.aggregate("train"):
        t1.train_step([batch])
        t2.train_step([batch])
    _assert_params_close(t1, t2, atol=1e-7)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_seq_parallel_matches_pure_dp(rng, impl):
    batch = make_batch(rng, bsz=16)
    t_dp = run_one_step(batch)
    t_sp = run_one_step(batch, seq_parallel_size=2, seq_parallel_impl=impl)
    # ring/Ulysses online softmax accumulates in a different order than the
    # fused local softmax: allow small fp32 slack
    _assert_params_close(t_dp, t_sp, atol=2e-4)


def test_seq_parallel_shards_tokens(rng):
    batch = make_batch(rng, bsz=16)
    t = run_one_step(batch, seq_parallel_size=2)
    put = t._to_device(t._prepare_sample_host(batch))
    spec = put["net_input"]["src_tokens"].sharding.spec
    assert "seq" in str(spec), spec
