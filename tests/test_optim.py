"""Optimizer parity vs torch.optim (the independent oracle) and
scheduler/scaler behavior tests."""

from argparse import Namespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from unicore_tpu.optim import OPTIMIZER_REGISTRY, build_optimizer
from unicore_tpu.optim.dynamic_loss_scaler import (
    DynamicLossScaler,
    scaler_init,
    scaler_update,
)
from unicore_tpu.optim.fp16_optimizer import (
    grads_finite,
    make_master_params,
    sync_master_to_model,
)
from unicore_tpu.optim.lr_scheduler import LR_SCHEDULER_REGISTRY, build_lr_scheduler


def _run_steps(opt, params, grad_seq, lr):
    state = opt.init(params)
    for g in grad_seq:
        updates, state = opt.update(g, state, params, lr=lr)
        params = jax.tree_util.tree_map(
            lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype), params, updates
        )
    return params


def _torch_steps(torch_opt_cls, tensors, grad_seq, **kw):
    ps = [torch.nn.Parameter(torch.from_numpy(t.copy())) for t in tensors]
    opt = torch_opt_cls(ps, **kw)
    for gs in grad_seq:
        for p, g in zip(ps, gs):
            p.grad = torch.from_numpy(np.asarray(g).copy())
        opt.step()
    return [p.detach().numpy() for p in ps]


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adam_matches_torch_adamw(rng, wd):
    t1, t2 = rng.randn(7, 5).astype(np.float32), rng.randn(13).astype(np.float32)
    grads = [
        (rng.randn(7, 5).astype(np.float32), rng.randn(13).astype(np.float32))
        for _ in range(5)
    ]
    args = Namespace(lr=[1e-2], adam_betas="(0.9, 0.98)", adam_eps=1e-8,
                     weight_decay=wd)
    opt = OPTIMIZER_REGISTRY["adam"](args)
    params = {"a": jnp.asarray(t1), "b": jnp.asarray(t2)}
    out = _run_steps(
        opt, params, [{"a": jnp.asarray(g[0]), "b": jnp.asarray(g[1])} for g in grads],
        lr=1e-2,
    )
    ref = _torch_steps(
        torch.optim.AdamW, [t1, t2], grads,
        lr=1e-2, betas=(0.9, 0.98), eps=1e-8, weight_decay=wd,
    )
    np.testing.assert_allclose(np.asarray(out["a"]), ref[0], atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["b"]), ref[1], atol=1e-5)


@pytest.mark.parametrize("momentum,wd", [(0.0, 0.0), (0.9, 0.01)])
def test_sgd_matches_torch(rng, momentum, wd):
    t = rng.randn(6, 4).astype(np.float32)
    grads = [rng.randn(6, 4).astype(np.float32) for _ in range(4)]
    args = Namespace(lr=[0.1], momentum=momentum, weight_decay=wd)
    opt = OPTIMIZER_REGISTRY["sgd"](args)
    out = _run_steps(opt, {"p": jnp.asarray(t)},
                     [{"p": jnp.asarray(g)} for g in grads], lr=0.1)
    ref = _torch_steps(torch.optim.SGD, [t], [[g] for g in grads],
                       lr=0.1, momentum=momentum, weight_decay=wd)
    np.testing.assert_allclose(np.asarray(out["p"]), ref[0], atol=1e-6)


def test_adagrad_matches_torch(rng):
    t = rng.randn(5, 3).astype(np.float32)
    grads = [rng.randn(5, 3).astype(np.float32) for _ in range(4)]
    args = Namespace(lr=[0.05], weight_decay=0.0)
    opt = OPTIMIZER_REGISTRY["adagrad"](args)
    out = _run_steps(opt, {"p": jnp.asarray(t)},
                     [{"p": jnp.asarray(g)} for g in grads], lr=0.05)
    ref = _torch_steps(torch.optim.Adagrad, [t], [[g] for g in grads], lr=0.05)
    np.testing.assert_allclose(np.asarray(out["p"]), ref[0], atol=1e-6)


def test_adadelta_matches_torch(rng):
    t = rng.randn(5, 3).astype(np.float32)
    grads = [rng.randn(5, 3).astype(np.float32) for _ in range(4)]
    args = Namespace(lr=[1.0], adadelta_rho=0.9, adadelta_eps=1e-6, weight_decay=0.0)
    opt = OPTIMIZER_REGISTRY["adadelta"](args)
    out = _run_steps(opt, {"p": jnp.asarray(t)},
                     [{"p": jnp.asarray(g)} for g in grads], lr=1.0)
    ref = _torch_steps(torch.optim.Adadelta, [t], [[g] for g in grads],
                       lr=1.0, rho=0.9, eps=1e-6)
    np.testing.assert_allclose(np.asarray(out["p"]), ref[0], atol=1e-6)


def test_optimizer_registry_contents():
    for name in ("adam", "sgd", "adagrad", "adadelta"):
        assert name in OPTIMIZER_REGISTRY


def test_build_optimizer_from_args():
    args = Namespace(optimizer="adam", lr=[1e-3], adam_betas="(0.9, 0.999)",
                     adam_eps=1e-8, weight_decay=0.0)
    opt = build_optimizer(args)
    assert opt.__class__.__name__ == "UnicoreAdam"


# -- schedulers --------------------------------------------------------------


def _sched(name, opt_args=None, total=None, **kw):
    defaults = dict(lr=[1.0])
    defaults.update(kw)
    args = Namespace(**defaults)
    opt = OPTIMIZER_REGISTRY["adam"](
        Namespace(lr=args.lr, adam_betas="(0.9, 0.999)", adam_eps=1e-8,
                  weight_decay=0.0)
    )
    return LR_SCHEDULER_REGISTRY[name](args, opt, total)


def test_schedules_jit_compatible():
    """The pure schedule functions trace under jit (branchless via where)
    and agree with their host-float values — the property that lets a
    training setup fold LR computation into the compiled step."""
    from unicore_tpu.optim.lr_scheduler import schedules

    f = jax.jit(lambda s: schedules.polynomial_decay(
        s, base_lr=1e-4, end_lr=0.0, power=1.0, warmup_updates=10,
        total_updates=110))
    np.testing.assert_allclose(float(f(jnp.int32(5))), 1e-4 * 0.5, rtol=1e-6)
    np.testing.assert_allclose(float(f(jnp.int32(60))), 1e-4 * 0.5, rtol=1e-6)
    np.testing.assert_allclose(float(f(jnp.int32(110))), 0.0, atol=1e-12)

    g = jax.jit(lambda s: schedules.cosine(
        s.astype(jnp.float32), max_lr=1.0, min_lr=0.0, period=100, t_mult=1,
        shrink=1.0, warmup_updates=0, warmup_init_lr=0.0))
    np.testing.assert_allclose(float(g(jnp.int32(50))), 0.5, atol=1e-6)

    h = jax.jit(lambda s: schedules.triangular(
        s.astype(jnp.float32), min_lr=0.1, max_lr=1.0, stepsize=50,
        shrink=1.0, shrink_min=False))
    np.testing.assert_allclose(float(h(jnp.int32(50))), 1.0, rtol=1e-6)


def test_cosine_tmult_warmup_no_domain_error():
    """t_mult != 1 with warmup longer than period/(t_mult-1): the annealing
    branch is evaluated unconditionally, so negative cycle time must be
    clamped before the log (regression: math domain error at step 0)."""
    from unicore_tpu.optim.lr_scheduler import schedules

    kw = dict(max_lr=1.0, min_lr=0.0, period=5000, t_mult=2, shrink=1.0,
              warmup_updates=10000, warmup_init_lr=0.0)
    np.testing.assert_allclose(schedules.cosine(0, **kw), 0.0, atol=1e-12)
    np.testing.assert_allclose(schedules.cosine(5000, **kw), 0.5, rtol=1e-6)
    np.testing.assert_allclose(schedules.cosine(10000, **kw), 1.0, rtol=1e-6)


def test_scheduler_registry_contents():
    for name in (
        "fixed", "cosine", "inverse_sqrt", "polynomial_decay",
        "exponential_decay", "triangular", "tri_stage",
        "reduce_lr_on_plateau", "pass_through",
    ):
        assert name in LR_SCHEDULER_REGISTRY


def test_fixed_schedule_warmup():
    s = _sched("fixed", lr=[2.0], force_anneal=None, lr_shrink=0.1,
               warmup_updates=10)
    s.step_begin_epoch(1)
    lrs = [s.step_update(i) for i in range(12)]
    np.testing.assert_allclose(lrs[0], 0.2)
    np.testing.assert_allclose(lrs[9], 2.0)
    np.testing.assert_allclose(lrs[11], 2.0)


def test_inverse_sqrt_schedule():
    s = _sched("inverse_sqrt", lr=[1e-3], warmup_updates=100, warmup_init_lr=-1)
    lr_w = s.step_update(50)
    np.testing.assert_allclose(lr_w, 1e-3 * 50 / 100, rtol=1e-6)
    lr_after = s.step_update(400)
    np.testing.assert_allclose(lr_after, 1e-3 * (100 ** 0.5) * 400 ** -0.5, rtol=1e-6)


def test_polynomial_decay_schedule():
    s = _sched("polynomial_decay", lr=[1e-4], warmup_updates=10, warmup_ratio=-1.0,
               end_learning_rate=0.0, power=1.0, total_num_update=110,
               force_anneal=None)
    np.testing.assert_allclose(s.step_update(5), 1e-4 * 0.5, rtol=1e-6)
    np.testing.assert_allclose(s.step_update(60), 1e-4 * 0.5, rtol=1e-6)
    np.testing.assert_allclose(s.step_update(110), 0.0, atol=1e-12)


def test_polynomial_decay_warmup_ratio_uses_total_steps():
    s = _sched("polynomial_decay", total=1000, lr=[1e-4], warmup_updates=0,
               warmup_ratio=0.1, end_learning_rate=0.0, power=1.0,
               total_num_update=0, force_anneal=None)
    assert s.warmup_updates == 100
    assert s.total_num_update == 1000


def test_cosine_schedule():
    s = _sched("cosine", lr=[1.0], warmup_updates=0, warmup_init_lr=-1,
               min_lr=0.0, max_lr=None, t_mult=1, lr_period_updates=100,
               lr_shrink=1.0, max_update=0)
    np.testing.assert_allclose(s.step_update(0), 1.0, rtol=1e-6)
    np.testing.assert_allclose(s.step_update(50), 0.5, atol=1e-6)
    np.testing.assert_allclose(s.step_update(100), 1.0, rtol=1e-6)  # new cycle


def test_exponential_decay_schedule():
    s = _sched("exponential_decay", lr=[1.0], warmup_updates=0, decay_ratio=0.5,
               decay_steps=10, stair_decay=True)
    np.testing.assert_allclose(s.step_update(25), 0.25, rtol=1e-6)


def test_triangular_schedule():
    s = _sched("triangular", lr=[0.1], max_lr=1.0, lr_period_updates=100,
               lr_shrink=1.0, shrink_min=False)
    np.testing.assert_allclose(s.step_update(0), 0.1, rtol=1e-6)
    np.testing.assert_allclose(s.step_update(50), 1.0, rtol=1e-6)
    np.testing.assert_allclose(s.step_update(100), 0.1, rtol=1e-6)


def test_tri_stage_schedule():
    s = _sched("tri_stage", lr=[1.0], warmup_steps=10, hold_steps=10,
               decay_steps=10, phase_ratio=None, init_lr_scale=0.01,
               final_lr_scale=0.01, max_update=0)
    np.testing.assert_allclose(s.step_update(0), 0.01, rtol=1e-5)
    np.testing.assert_allclose(s.step_update(10), 1.0, rtol=1e-5)
    np.testing.assert_allclose(s.step_update(15), 1.0, rtol=1e-5)
    np.testing.assert_allclose(s.step_update(1000), 0.01, rtol=1e-5)


def test_reduce_lr_on_plateau():
    s = _sched("reduce_lr_on_plateau", lr=[1.0], lr_shrink=0.5, lr_threshold=1e-4,
               lr_patience=0, warmup_updates=0, warmup_init_lr=-1)
    s.step(1, val_loss=1.0)
    assert s.optimizer.get_lr() == 1.0
    s.step(2, val_loss=0.5)  # improvement
    assert s.optimizer.get_lr() == 1.0
    s.step(3, val_loss=0.5)  # plateau -> shrink
    np.testing.assert_allclose(s.optimizer.get_lr(), 0.5)


def test_scheduler_state_roundtrip():
    s = _sched("fixed", lr=[2.0], force_anneal=None, lr_shrink=0.1,
               warmup_updates=0)
    s.step_begin_epoch(1)
    sd = s.state_dict()
    s2 = _sched("fixed", lr=[2.0], force_anneal=None, lr_shrink=0.1,
                warmup_updates=0)
    s2.load_state_dict(sd)
    assert s2.lr == s.lr


# -- loss scaler -------------------------------------------------------------


def test_host_scaler_overflow_flow():
    s = DynamicLossScaler(init_scale=16.0, scale_window=2, min_loss_scale=0.25)
    with pytest.raises(OverflowError):
        s.check_overflow(float("inf"))
    assert s.loss_scale == 8.0
    with pytest.raises(OverflowError):
        s.check_overflow(float("nan"))
    assert s.loss_scale == 4.0
    # clean steps grow after window
    start = s.loss_scale
    s.update()
    s.update()
    assert s.loss_scale >= start


def test_host_scaler_min_scale_abort():
    s = DynamicLossScaler(init_scale=0.5, scale_window=2, min_loss_scale=0.3)
    with pytest.raises(FloatingPointError):
        s.check_overflow(float("inf"))


def test_functional_scaler():
    st = scaler_init(16.0)
    st = scaler_update(st, jnp.asarray(True), scale_window=2)
    assert float(st["scale"]) == 8.0
    st = scaler_update(st, jnp.asarray(False), scale_window=2)
    st = scaler_update(st, jnp.asarray(False), scale_window=2)
    assert float(st["scale"]) == 16.0  # grew after 2 clean steps


# -- mixed precision helpers --------------------------------------------------


def test_master_copy_roundtrip(rng):
    p = {"w": jnp.asarray(rng.randn(33, 5).astype(np.float32), dtype=jnp.bfloat16)}
    master = make_master_params(p)
    assert master["w"].dtype == jnp.float32
    model = sync_master_to_model(master, jnp.bfloat16)
    assert model["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(model["w"], dtype=np.float32),
        np.asarray(p["w"], dtype=np.float32),
    )


def test_sync_with_stochastic_rounding(rng):
    x = np.full((4096,), 1.0 + 1.0 / 512.0, dtype=np.float32)
    master = {"w": jnp.asarray(x)}
    model = sync_master_to_model(master, jnp.bfloat16, sr_rng=jax.random.PRNGKey(0))
    vals = np.asarray(model["w"], dtype=np.float32)
    assert set(np.unique(vals)) == {1.0, 1.0078125}


def test_grads_finite():
    good = {"a": jnp.ones((3,)), "b": jnp.zeros((2, 2))}
    bad = {"a": jnp.asarray([1.0, jnp.inf, 0.0]), "b": jnp.zeros((2, 2))}
    assert bool(grads_finite(good))
    assert not bool(grads_finite(bad))


def test_sr_cast_straight_through_gradient():
    """--bf16-sr's in-loss cast: value is the SR rounding, gradient is
    identity to the fp32 master."""
    import jax
    import jax.numpy as jnp

    from unicore_tpu.optim.fp16_optimizer import _sr_cast_straight_through

    x = jnp.linspace(-3.0, 3.0, 64, dtype=jnp.float32)
    key = jax.random.PRNGKey(0)

    def f(x):
        return jnp.sum(_sr_cast_straight_through(x, key).astype(jnp.float32))

    g = jax.grad(f)(x)
    np.testing.assert_allclose(np.asarray(g), np.ones_like(x), atol=0)
    out = _sr_cast_straight_through(x, key)
    assert out.dtype == jnp.bfloat16
    # value matches the raw SR op
    from unicore_tpu.ops import fp32_to_bf16_sr

    np.testing.assert_array_equal(
        np.asarray(out, dtype=np.float32),
        np.asarray(fp32_to_bf16_sr(x, key), dtype=np.float32),
    )
