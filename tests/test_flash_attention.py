"""Flash attention kernel vs materialized reference — fwd, grads (incl.
bias), padding, causal, dropout statistics, and module-level dispatch
equivalence.  Runs in interpret mode on CPU; with
UNICORE_TPU_TEST_ON_TPU=1 it compiles and runs on the real chip, where
tolerances widen to MXU fp32 matmul precision (inputs pass through
bf16 lanes, so independent accumulation orders differ at ~1e-4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu.ops.backend import kernel_backend
from unicore_tpu.ops.pallas.flash_attention import eligible, flash_attention

B, T, H, D = 2, 256, 4, 64

ON_TPU = os.environ.get("UNICORE_TPU_TEST_ON_TPU", "") == "1"
# On the chip the error model is relative (MXU bf16-lane passes), so
# tolerance is rtol-led; in interpret mode both sides are exact fp32 and
# atol-led tight bounds apply.
FWD_TOL = dict(rtol=2e-2, atol=5e-3) if ON_TPU else dict(atol=2e-5)
GRAD_TOL = dict(rtol=2e-2, atol=2e-2) if ON_TPU else dict(atol=5e-4)


def ref_attn(q, k, v, bias=None, pad=None, causal=False, scale=None):
    scale = D ** -0.5 if scale is None else scale
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if bias is not None:
        s = s + bias
    if pad is not None:
        s = jnp.where(pad.astype(bool)[:, None, None, :], -1e30, s)
    if causal:
        m = jnp.triu(jnp.full((q.shape[1], k.shape[1]), -1e30), k=1)
        s = s + m[None, None]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.transpose(jnp.einsum("bhqk,bhkd->bhqd", p, vt), (0, 2, 1, 3))


@pytest.fixture
def qkv(rng):
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("case", ["plain", "bias", "pad", "bias+pad", "causal"])
def test_flash_forward_parity(rng, qkv, case):
    q, k, v = qkv
    kw, refkw = {}, {}
    if "bias" in case:
        bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
        kw["bias"] = refkw["bias"] = bias
    if "pad" in case:
        pad = np.zeros((B, T), dtype=np.int32)
        pad[:, -32:] = 1
        kw["key_padding_mask"] = jnp.asarray(pad)
        refkw["pad"] = jnp.asarray(pad)
    if case == "causal":
        kw["causal"] = refkw["causal"] = True
    out = flash_attention(q, k, v, is_training=False, **kw)
    ref = ref_attn(q, k, v, **refkw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **FWD_TOL)


def test_flash_grad_parity(rng, qkv):
    q, k, v = qkv
    bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
    pad = np.zeros((B, T), dtype=np.int32)
    pad[:, -32:] = 1
    pad = jnp.asarray(pad)

    def lf(q, k, v, bias):
        return jnp.sum(
            flash_attention(q, k, v, bias=bias, key_padding_mask=pad,
                            is_training=False) ** 2
        )

    def lr(q, k, v, bias):
        return jnp.sum(ref_attn(q, k, v, bias=bias, pad=pad) ** 2)

    g1 = jax.grad(lf, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(lr, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for name, a, b in zip("q k v bias".split(), g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), err_msg=name, **GRAD_TOL
        )


def test_flash_dropout_deterministic_and_distributed(rng, qkv):
    q, k, v = qkv
    key = jax.random.PRNGKey(5)
    o1 = flash_attention(q, k, v, dropout_prob=0.3, rng=key, is_training=True)
    o2 = flash_attention(q, k, v, dropout_prob=0.3, rng=key, is_training=True)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = flash_attention(q, k, v, dropout_prob=0.3, rng=jax.random.PRNGKey(6),
                         is_training=True)
    assert np.abs(np.asarray(o1) - np.asarray(o3)).max() > 1e-4
    # dropout changes the output vs no-dropout
    o4 = flash_attention(q, k, v, is_training=False)
    assert np.abs(np.asarray(o1) - np.asarray(o4)).max() > 1e-4


def test_flash_dropout_grads_finite(rng, qkv):
    q, k, v = qkv
    key = jax.random.PRNGKey(0)

    def loss(q):
        return jnp.sum(
            flash_attention(q, k, v, dropout_prob=0.2, rng=key,
                            is_training=True) ** 2
        )

    g = jax.grad(loss)(q)
    assert bool(jnp.all(jnp.isfinite(g)))


def test_flash_multiblock_grad_parity(rng, qkv, monkeypatch):
    """Pin small blocks so T=256 spans a 2x2 block grid: covers the
    cross-k-block online-softmax rescale in the forward and the
    scratch-accumulating three-pass backward (dq, dkv, dbias) — the
    long-context path.  (At the natural block pick T=256 is single-block
    and takes the fused backward, which the other tests cover.)"""
    import unicore_tpu.ops.pallas.flash_attention as fa

    monkeypatch.setattr(
        fa, "_pick_blocks", lambda tq, tk, bias_itemsize=0: (128, 128)
    )
    q, k, v = qkv
    bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
    pad = np.zeros((B, T), dtype=np.int32)
    pad[:, -32:] = 1
    pad = jnp.asarray(pad)

    out = flash_attention(q, k, v, bias=bias, key_padding_mask=pad,
                          is_training=False)
    ref = ref_attn(q, k, v, bias=bias, pad=pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **FWD_TOL)

    def lf(q, k, v, bias):
        return jnp.sum(
            flash_attention(q, k, v, bias=bias, key_padding_mask=pad,
                            is_training=False) ** 2
        )

    def lr(q, k, v, bias):
        return jnp.sum(ref_attn(q, k, v, bias=bias, pad=pad) ** 2)

    g1 = jax.grad(lf, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(lr, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for name, a, b in zip("q k v bias".split(), g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), err_msg=name, **GRAD_TOL
        )


def test_flash_joint_backward_parity(rng, qkv, monkeypatch):
    """Pin blocks so T=256 spans a 2x1 grid (n_q=2, n_k=1): the regime of
    the JOINT one-pass backward (dq + dk + dv, full-K dk/dv scratch) that
    replaced the dq/dkv two-pass for single-k-block shapes like T=2048."""
    import unicore_tpu.ops.pallas.flash_attention as fa

    monkeypatch.setattr(
        fa, "_pick_blocks", lambda tq, tk, bias_itemsize=0: (128, 256)
    )
    q, k, v = qkv
    bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
    pad = np.zeros((B, T), dtype=np.int32)
    pad[:, -32:] = 1
    pad = jnp.asarray(pad)

    out = flash_attention(q, k, v, bias=bias, key_padding_mask=pad,
                          is_training=False)
    ref = ref_attn(q, k, v, bias=bias, pad=pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **FWD_TOL)

    def lf(q, k, v, bias):
        return jnp.sum(
            flash_attention(q, k, v, bias=bias, key_padding_mask=pad,
                            is_training=False) ** 2
        )

    def lr(q, k, v, bias):
        return jnp.sum(ref_attn(q, k, v, bias=bias, pad=pad) ** 2)

    g1 = jax.grad(lf, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(lr, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for name, a, b in zip("q k v bias".split(), g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), err_msg=name, **GRAD_TOL
        )


def test_eligibility_rules():
    assert eligible((2, 4, 256, 64), (2, 4, 256, 64), None)
    assert eligible((2, 4, 256, 64), (2, 4, 256, 64), (1, 4, 256, 256))
    # batched bias -> materialized fallback
    assert not eligible((2, 4, 256, 64), (2, 4, 256, 64), (2, 4, 256, 256))
    # non-128-multiple seq
    assert not eligible((2, 4, 200, 64), (2, 4, 200, 64), None)


def test_module_dispatch_equivalence(rng):
    """SelfMultiheadAttention must produce identical results via the flash
    path (forced pallas backend) and the einsum path."""
    from unicore_tpu.modules import SelfMultiheadAttention

    E, heads = 64, 2
    x = jnp.asarray(rng.randn(2, 128, E).astype(np.float32))
    bias = jnp.asarray(rng.randn(1, heads, 128, 128).astype(np.float32))
    pad = np.zeros((2, 128), dtype=np.int32)
    pad[:, -16:] = 1
    attn = SelfMultiheadAttention(embed_dim=E, num_heads=heads, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), x)
    with kernel_backend("reference"):
        o_ref = attn.apply(params, x, key_padding_mask=jnp.asarray(pad),
                           attn_bias=bias)
    with kernel_backend("pallas"):
        o_flash = attn.apply(params, x, key_padding_mask=jnp.asarray(pad),
                             attn_bias=bias)
    np.testing.assert_allclose(
        np.asarray(o_ref), np.asarray(o_flash), **FWD_TOL
    )


def test_module_dispatch_equivalence_causal(rng):
    """The decoder path: causal=True must agree between the flash kernel
    (forced pallas) and the einsum + iota-mask reference path, including
    gradients (the causal flag replaces the reference's materialized
    future-mask merge)."""
    from unicore_tpu.modules import SelfMultiheadAttention

    E, heads = 64, 2
    x = jnp.asarray(rng.randn(2, 128, E).astype(np.float32))
    bias = jnp.asarray(rng.randn(1, heads, 128, 128).astype(np.float32))
    attn = SelfMultiheadAttention(embed_dim=E, num_heads=heads, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), x)

    def loss(p, backend):
        with kernel_backend(backend):
            o = attn.apply(p, x, attn_bias=bias, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    (l_ref, o_ref), g_ref = jax.value_and_grad(loss, has_aux=True)(
        params, "reference"
    )
    (l_fl, o_fl), g_fl = jax.value_and_grad(loss, has_aux=True)(
        params, "pallas"
    )
    np.testing.assert_allclose(np.asarray(o_ref), np.asarray(o_fl), **FWD_TOL)

    def check(a, b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **GRAD_TOL)

    jax.tree_util.tree_map(check, g_ref, g_fl)


def test_flash_dropout_row_seed_global_identity(rng, qkv):
    """Per-row dropout seeds carry global row identity: a shard computing
    rows [2:4] with batch_seed_offset=2 must reproduce the full batch's
    rows [2:4] exactly — and a shard without the offset must NOT (this is
    the per-shard mask decorrelation under data sharding)."""
    q, k, v = qkv
    q4 = jnp.concatenate([q, q], axis=0)  # B=4, rows 2:4 duplicate 0:2
    k4 = jnp.concatenate([k, k], axis=0)
    v4 = jnp.concatenate([v, v], axis=0)
    key = jax.random.PRNGKey(11)
    full = flash_attention(q4, k4, v4, dropout_prob=0.3, rng=key,
                           is_training=True)
    shard_hi = flash_attention(q, k, v, dropout_prob=0.3, rng=key,
                               is_training=True, batch_seed_offset=2)
    np.testing.assert_allclose(
        np.asarray(full[2:4]), np.asarray(shard_hi), atol=1e-6
    )
    shard_lo = flash_attention(q, k, v, dropout_prob=0.3, rng=key,
                               is_training=True)
    # identical inputs, different global rows -> different masks
    assert not np.allclose(np.asarray(shard_lo), np.asarray(shard_hi))
    # and within one call, duplicate rows get different masks too
    assert not np.allclose(np.asarray(full[:2]), np.asarray(full[2:4]))
