"""Trainer tests: loss decreases, grad accumulation equivalence, overflow
skip, EMA, checkpoint round-trip, multi-device sharding — the unit coverage
the reference never had (SURVEY §4 implication)."""

import os
from argparse import Namespace

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu import metrics
from unicore_tpu.losses.unicore_loss import UnicoreLoss
from unicore_tpu.models.unicore_model import BaseUnicoreModel
from unicore_tpu.tasks.unicore_task import UnicoreTask
from unicore_tpu.trainer import Trainer

VOCAB, DIM = 13, 16


class ToyModel(BaseUnicoreModel):
    @nn.compact
    def __call__(self, src_tokens, deterministic=True, **kwargs):
        x = nn.Embed(VOCAB, DIM, name="embed")(src_tokens)
        return nn.Dense(VOCAB, name="out")(x)


class ToyLoss(UnicoreLoss):
    """Identity LM: predict the input token at each position."""

    def forward(self, model, params, sample, rng=None, is_training=True):
        logits = model.apply(
            {"params": params}, **sample["net_input"],
            deterministic=not is_training,
        )
        lprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        target = sample["target"]
        nll = -jnp.take_along_axis(lprobs, target[..., None], axis=-1)[..., 0]
        loss = jnp.sum(nll)
        n = jnp.asarray(np.prod(target.shape), dtype=jnp.float32)
        return loss, n, {"loss": loss, "bsz": jnp.float32(target.shape[0]),
                         "sample_size": n}

    @staticmethod
    def reduce_metrics(logging_outputs, split="train"):
        loss = sum(float(l.get("loss", 0)) for l in logging_outputs)
        n = sum(float(l.get("sample_size", 0)) for l in logging_outputs)
        metrics.log_scalar("loss", loss / max(n, 1), n, round=3)

    @staticmethod
    def logging_outputs_can_be_summed(is_train):
        return True


class ToyTask(UnicoreTask):
    pass


def make_args(**over):
    d = dict(
        seed=1, update_freq=[1], clip_norm=0.0, ema_decay=-1.0,
        fp16=False, bf16=False, bf16_sr=False,
        optimizer="adam", lr=[1e-2], adam_betas="(0.9, 0.999)",
        adam_eps=1e-8, weight_decay=0.0,
        lr_scheduler="fixed", force_anneal=None, lr_shrink=0.1,
        warmup_updates=0, min_loss_scale=1e-4, fp16_scale_window=None,
        fp16_init_scale=4.0, max_update=100, max_epoch=0,
        tensor_parallel_size=1, seq_parallel_size=1, fsdp_size=1,
    )
    d.update(over)
    return Namespace(**d)


def make_batch(rng, bsz=8, seq=8):
    toks = rng.randint(0, VOCAB, size=(bsz, seq)).astype(np.int64)
    return {"net_input": {"src_tokens": toks}, "target": toks.copy()}


def make_trainer(**over):
    args = make_args(**over)
    task = ToyTask(args)
    return Trainer(args, task, ToyModel(), ToyLoss(task))


def test_train_step_decreases_loss(rng):
    metrics.reset()
    trainer = make_trainer()
    batch = make_batch(rng)
    losses = []
    with metrics.aggregate("train"):
        for _ in range(30):
            logs = trainer.train_step([batch])
            losses.append(float(logs[0]["loss"]))
    # identity mapping is learnable: loss must drop substantially
    assert losses[-1] < losses[0] * 0.5
    assert trainer.get_num_updates() == 30


def test_grad_accumulation_equivalence(rng):
    """update_freq=2 over two half-batches == one full batch step."""
    metrics.reset()
    full = make_batch(rng, bsz=8)
    half1 = {
        "net_input": {"src_tokens": full["net_input"]["src_tokens"][:4]},
        "target": full["target"][:4],
    }
    half2 = {
        "net_input": {"src_tokens": full["net_input"]["src_tokens"][4:]},
        "target": full["target"][4:],
    }
    with metrics.aggregate("train"):
        t1 = make_trainer(update_freq=[2])
        t1.train_step([half1, half2])
        p1 = jax.device_get(t1.state["params"])

        t2 = make_trainer()
        t2.train_step([full])
        p2 = jax.device_get(t2.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_dummy_batch_ignore_grad(rng):
    """Short micro-batch lists are padded with zero-weight dummy batches
    (the reference's empty-shard lockstep protocol)."""
    metrics.reset()
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        t1 = make_trainer(update_freq=[2])
        t1.train_step([batch])  # only one of two micro-batches present
        p1 = jax.device_get(t1.state["params"])
        t2 = make_trainer(update_freq=[1])
        t2.train_step([batch])
        p2 = jax.device_get(t2.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_fp16_overflow_skip(rng):
    """Non-finite grads must skip the update and halve the loss scale."""
    metrics.reset()
    trainer = make_trainer(fp16=True, fp16_init_scale=4.0)
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])  # init + one good step
    params_before = jax.device_get(trainer.state["params"])
    scale_before = float(trainer.state["scaler"]["scale"])

    bad = {
        "net_input": {"src_tokens": batch["net_input"]["src_tokens"]},
        "target": batch["target"],
    }
    # poison the embedding so grads go non-finite
    poisoned = jax.device_get(trainer.state["params"])
    poisoned["embed"]["embedding"] = np.full_like(
        poisoned["embed"]["embedding"], np.inf
    )
    from unicore_tpu.distributed import replicated

    trainer.state["params"] = jax.device_put(
        jax.tree_util.tree_map(jnp.asarray, poisoned), replicated(trainer.mesh)
    )
    n_before = trainer.get_num_updates()
    with metrics.aggregate("train"):
        trainer.train_step([bad])
    assert trainer.get_num_updates() == n_before  # skipped
    assert float(trainer.state["scaler"]["scale"]) == scale_before / 2.0


def test_ema_tracks_params(rng):
    metrics.reset()
    trainer = make_trainer(ema_decay=0.5)
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        for _ in range(3):
            trainer.train_step([batch])
    ema = jax.device_get(trainer.state["ema"])
    params = jax.device_get(trainer.state["params"])
    # ema lags but is finite and different from params
    diff = sum(
        float(np.abs(a - b).sum())
        for a, b in zip(
            jax.tree_util.tree_leaves(ema), jax.tree_util.tree_leaves(params)
        )
    )
    assert np.isfinite(diff) and diff > 0


def test_checkpoint_roundtrip(rng, tmp_path):
    metrics.reset()
    t1 = make_trainer()
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        for _ in range(3):
            t1.train_step([batch])
    fn = os.path.join(str(tmp_path), "ckpt.pt")
    t1.save_checkpoint(fn, {"train_iterator": {"epoch": 1}})

    t2 = make_trainer()
    extra = t2.load_checkpoint(fn)
    assert extra["train_iterator"]["epoch"] == 1
    assert t2.get_num_updates() == 3
    # restore is deferred until shapes are known; init_state materializes
    t2.init_state(batch)
    p1 = jax.device_get(t1.state["params"])
    p2 = jax.device_get(t2.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(a, b)
    # resumed trainer continues training bit-exactly vs uninterrupted one
    with metrics.aggregate("train"):
        t1.train_step([batch])
        t2.train_step([batch])
    q1 = jax.device_get(t1.state["params"])
    q2 = jax.device_get(t2.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(q1), jax.tree_util.tree_leaves(q2)):
        np.testing.assert_allclose(a, b, atol=1e-7)


def test_multidevice_batch_sharding(rng):
    """The batch really is sharded over all devices of the mesh.  The
    sharded-vs-single-device math invariant lives in
    tests/test_fsdp_seq.py::test_one_device_vs_eight_device_update."""
    metrics.reset()
    n_dev = len(jax.devices())
    if n_dev < 8:
        pytest.skip("needs the virtual 8-device mesh")
    batch = make_batch(rng, bsz=16)
    with metrics.aggregate("train"):
        t1 = make_trainer()
        t1.train_step([batch])
    sharded = t1._to_device(t1._prepare_sample_host(batch))
    tok_sharding = sharded["net_input"]["src_tokens"].sharding
    assert len(tok_sharding.device_set) == n_dev


def test_bf16_compute_dtype(rng):
    metrics.reset()
    trainer = make_trainer(bf16=True)
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        logs = trainer.train_step([batch])
    assert np.isfinite(logs[0]["loss"])
    # master params stay fp32
    for p in jax.tree_util.tree_leaves(trainer.state["params"]):
        assert p.dtype == jnp.float32


def test_nonscaler_nan_aborts_with_detector(rng, caplog):
    """bf16/fp32 runs must abort (not silently skip) on non-finite grads,
    after naming the offending module (reference NanDetector semantics)."""
    metrics.reset()
    trainer = make_trainer()  # fp32, no scaler
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])
    poisoned = jax.device_get(trainer.state["params"])
    poisoned["embed"]["embedding"] = np.full_like(
        poisoned["embed"]["embedding"], np.inf
    )
    from unicore_tpu.distributed import replicated

    trainer.state["params"] = jax.device_put(
        jax.tree_util.tree_map(jnp.asarray, poisoned), replicated(trainer.mesh)
    )
    with metrics.aggregate("train"):
        with pytest.raises(FloatingPointError):
            trainer.train_step([batch])


def test_nan_detector_names_module(rng):
    from unicore_tpu.nan_detector import find_nonfinite_modules

    model = ToyModel()
    batch = make_batch(rng)
    params = model.init(
        jax.random.PRNGKey(0), jnp.asarray(batch["net_input"]["src_tokens"])
    )["params"]
    params["out"]["kernel"] = jnp.full_like(params["out"]["kernel"], jnp.nan)
    bad = find_nonfinite_modules(model, params, batch)
    assert any("out" in name for name, _ in bad)


def test_bf16_sr_training_differs_from_plain_bf16(rng):
    """--bf16-sr must actually change training (VERDICT r1: the flag was
    decorative).  Same data: SR and plain bf16 runs end with different
    (but both finite) params; SR runs are self-deterministic."""
    metrics.reset()
    batch = make_batch(rng)

    def run(**over):
        t = make_trainer(bf16=True, **over)
        with metrics.aggregate("train"):
            for _ in range(5):
                logs = t.train_step([batch])
        assert np.isfinite(logs[0]["loss"])
        return jax.device_get(t.state["params"])

    p_sr1 = run(bf16_sr=True)
    p_sr2 = run(bf16_sr=True)
    p_plain = run()
    flat = lambda p: np.concatenate(
        [np.ravel(np.asarray(l)) for l in jax.tree_util.tree_leaves(p)]
    )
    np.testing.assert_array_equal(flat(p_sr1), flat(p_sr2))
    assert not np.array_equal(flat(p_sr1), flat(p_plain))


class NonSummableLoss(ToyLoss):
    """Logging outputs must NOT be summed across micro-batches."""

    @staticmethod
    def logging_outputs_can_be_summed(is_train):
        return False


def make_nonsummable_trainer(**over):
    args = make_args(**over)
    task = ToyTask(args)
    return Trainer(args, task, ToyModel(), NonSummableLoss(task))


def test_nonsummable_logging_outputs_per_microbatch(rng):
    """When logging_outputs_can_be_summed is False the trainer must hand
    reduce_metrics one dict per real micro-batch, not a single sum
    (VERDICT r1 item 7)."""
    metrics.reset()
    t = make_nonsummable_trainer(update_freq=[3])
    b1, b2 = make_batch(rng, bsz=4), make_batch(rng, bsz=4)
    with metrics.aggregate("train"):
        logs = t.train_step([b1, b2])  # 3rd slot is a dummy (weight 0)
    assert len(logs) == 2  # one per REAL micro-batch, dummy dropped
    # each entry carries its own micro-batch stats, unsummed
    for entry in logs:
        assert float(entry["bsz"]) == 4.0
    # and the math matches the summable path: same data, same params
    metrics.reset()
    t2 = make_trainer(update_freq=[3])
    with metrics.aggregate("train"):
        logs2 = t2.train_step([b1, b2])
    assert len(logs2) == 1 and float(logs2[0]["bsz"]) == 8.0
    p1 = jax.device_get(t.state["params"])
    p2 = jax.device_get(t2.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_all_gather_objects_single_process():
    from unicore_tpu.distributed import all_gather_objects

    obj = {"loss": 1.5, "ids": [1, 2, 3]}
    assert all_gather_objects(obj) == [obj]


def test_per_sample_clip_norm(rng):
    """--per-sample-clip-norm clips each example's gradient before
    accumulation (reference unicore_optimizer.py:110-130, redesigned to
    true per-example granularity under SPMD)."""
    metrics.reset()
    batch = make_batch(rng, bsz=4)
    # tiny threshold: every per-example grad is scaled down, so the final
    # update must differ from the unclipped run and the effective global
    # grad norm must be bounded by bsz * threshold / sample_size-norm
    with metrics.aggregate("train"):
        t_clip = make_trainer(per_sample_clip_norm=1e-3)
        logs_c = t_clip.train_step([batch])
        t_plain = make_trainer()
        logs_p = t_plain.train_step([batch])
    # losses identical (clipping affects grads, not the forward)
    np.testing.assert_allclose(
        float(logs_c[0]["loss"]), float(logs_p[0]["loss"]), rtol=1e-5
    )
    p_c = jax.device_get(t_clip.state["params"])
    p_p = jax.device_get(t_plain.state["params"])
    flat = lambda p: np.concatenate(
        [np.ravel(np.asarray(l)) for l in jax.tree_util.tree_leaves(p)]
    )
    assert not np.allclose(flat(p_c), flat(p_p))
    # huge threshold: clipping is a no-op and must match plain exactly
    metrics.reset()
    with metrics.aggregate("train"):
        t_noop = make_trainer(per_sample_clip_norm=1e9)
        t_noop.train_step([batch])
    p_n = jax.device_get(t_noop.state["params"])
    np.testing.assert_allclose(flat(p_n), flat(p_p), atol=1e-6)


def test_legacy_in_proj_layout_restores(rng, tmp_path):
    """A checkpoint saved with the pre-r4 in_proj layout (Dense kernel
    [E, 3E] / bias [3E]) must load into the DenseGeneral [E, 3, H, Dh]
    model via the size-preserving reshape in the deferred restore."""
    import pickle

    from unicore_tpu.modules import SelfMultiheadAttention

    E, H = 16, 4

    class AttnModel(BaseUnicoreModel):
        @nn.compact
        def __call__(self, src_tokens, deterministic=True, **kwargs):
            x = nn.Embed(VOCAB, E, name="embed")(src_tokens)
            x = x + SelfMultiheadAttention(
                embed_dim=E, num_heads=H, dropout=0.0, name="attn"
            )(x, deterministic=deterministic)
            return nn.Dense(VOCAB, name="out")(x)

    def make(args):
        task = ToyTask(args)
        return Trainer(args, task, AttnModel(), ToyLoss(task))

    metrics.reset()
    batch = make_batch(rng)
    t1 = make(make_args())
    with metrics.aggregate("train"):
        t1.train_step([batch])
    fn = os.path.join(str(tmp_path), "legacy.pt")
    t1.save_checkpoint(fn, {"train_iterator": {"epoch": 1}})

    # rewrite the checkpoint into the legacy flat layout
    with open(fn, "rb") as f:
        ckpt = pickle.load(f)

    def flatten_in_proj(tree):
        for k, v in tree.items():
            if k == "in_proj":
                v["kernel"] = np.asarray(v["kernel"]).reshape(E, 3 * E)
                v["bias"] = np.asarray(v["bias"]).reshape(3 * E)
            elif isinstance(v, dict):
                flatten_in_proj(v)

    flatten_in_proj(ckpt["model"])
    with open(fn, "wb") as f:
        pickle.dump(ckpt, f)
    # a hand-rewritten checkpoint (like any external conversion tool's
    # output) carries no integrity sidecar; the stale one must go or the
    # verified read correctly rejects the edit as a torn file
    os.remove(fn + ".sum")

    t2 = make(make_args())
    t2.load_checkpoint(fn)
    t2.init_state(batch)  # merge reshapes kernel/bias (and adam moments)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(t1.state["params"])),
        jax.tree_util.tree_leaves(jax.device_get(t2.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and a checkpoint that REALLY mismatches fails with the path named
    ckpt["model"]["params"]["attn"]["in_proj"]["kernel"] = np.zeros((3, 3))
    with open(fn, "wb") as f:
        pickle.dump(ckpt, f)
    t3 = make(make_args())
    t3.load_checkpoint(fn)
    with pytest.raises(ValueError, match="in_proj/kernel"):
        t3.init_state(batch)


def test_tp_with_seq_parallel_fails_fast():
    with pytest.raises(NotImplementedError, match="tensor-parallel"):
        make_trainer(tensor_parallel_size=2, seq_parallel_size=2)


def test_reserved_parallel_flags_fail_fast():
    with pytest.raises(NotImplementedError, match="pipeline"):
        make_trainer(pipeline_parallel_size=2)
    with pytest.raises(NotImplementedError, match="expert"):
        make_trainer(expert_parallel_size=2)
