"""Data pipeline tests: record store, dictionary, masking, collation,
iterators with checkpoint resume (the reference has none of these — see
SURVEY.md §4 for why the rebuild adds them)."""

import numpy as np
import pytest

from unicore_tpu.data import (
    AppendTokenDataset,
    Dictionary,
    EpochShuffleDataset,
    IndexedRecordDataset,
    IndexedRecordWriter,
    MaskTokensDataset,
    NestedDictionaryDataset,
    NumelDataset,
    NumSamplesDataset,
    PrependTokenDataset,
    RightPadDataset,
    SortDataset,
    TokenizeDataset,
    UnicoreDataset,
    data_utils,
    iterators,
)


class ListDataset(UnicoreDataset):
    def __init__(self, items):
        self.items = items

    def __getitem__(self, i):
        return self.items[i]

    def __len__(self):
        return len(self.items)

    def collater(self, samples):
        return np.stack([np.asarray(s) for s in samples])


def make_dictionary():
    d = Dictionary()
    for sym in ["[CLS]", "[PAD]", "[SEP]", "[UNK]", "[MASK]"]:
        d.add_symbol(sym, is_special=True)
    for sym in list("abcdefgh"):
        d.add_symbol(sym)
    return d


def test_indexed_record_roundtrip(tmp_path):
    path = str(tmp_path / "data.rec")
    records = [{"x": np.arange(i + 1), "label": i} for i in range(10)]
    with IndexedRecordWriter(path) as w:
        for r in records:
            w.write(r)
    ds = IndexedRecordDataset(path)
    assert len(ds) == 10
    for i, r in enumerate(records):
        got = ds[i]
        np.testing.assert_array_equal(got["x"], r["x"])
        assert got["label"] == r["label"]


def test_dictionary_basics(tmp_path):
    d = make_dictionary()
    assert d.pad() == 1 and d.bos() == 0 and d.eos() == 2 and d.unk() == 3
    assert d.index("a") == 5
    assert d.index("never-seen") == d.unk()
    np.testing.assert_array_equal(d.vec_index(np.array(["a", "b"])), [5, 6])
    # save/load roundtrip
    p = str(tmp_path / "dict.txt")
    d.save(p)
    d2 = Dictionary.load(p)
    assert d2.index("a") == d.index("a")


def test_collate_tokens_padding():
    vals = [np.array([1, 2, 3]), np.array([4])]
    out = data_utils.collate_tokens(vals, pad_idx=0, pad_to_multiple=8)
    assert out.shape == (2, 8)
    np.testing.assert_array_equal(out[0], [1, 2, 3, 0, 0, 0, 0, 0])
    out = data_utils.collate_tokens(vals, pad_idx=0, left_pad=True, pad_to_length=4)
    assert out.shape == (2, 4)
    np.testing.assert_array_equal(out[1], [0, 0, 0, 4])


def test_collate_tokens_2d():
    vals = [np.ones((2, 2)), np.ones((3, 3))]
    out = data_utils.collate_tokens_2d(vals, pad_idx=0, pad_to_multiple=4)
    assert out.shape == (2, 4, 4)
    assert out[0, :2, :2].sum() == 4 and out[0].sum() == 4


def test_mask_tokens_dataset_deterministic():
    d = make_dictionary()
    base = ListDataset([np.array([5, 6, 7, 8, 5, 6, 7, 8, 5, 6], dtype=np.int64)] * 4)
    src, tgt = MaskTokensDataset.apply_mask(
        base, d, pad_idx=d.pad(), mask_idx=d.index("[MASK]"), seed=7, mask_prob=0.5
    )
    for ds in (src, tgt):
        ds.set_epoch(1)
    a1, t1 = src[0], tgt[0]
    a2, t2 = src[0], tgt[0]
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(t1, t2)
    # masked positions in target hold the original token; the rest are pad
    masked = t1 != d.pad()
    assert masked.sum() > 0
    orig = base[0]
    np.testing.assert_array_equal(t1[masked], orig[masked])
    # input differs from original only on mask-related positions
    changed = a1 != orig
    assert np.all(masked | ~changed)


def test_nested_dictionary_dataset():
    base = ListDataset([np.array([1, 2]), np.array([3, 4])])
    ds = NestedDictionaryDataset(
        {
            "net_input": {"src_tokens": RightPadDataset(base, pad_idx=0, pad_to_multiple=1)},
            "target": base,
            "nsamples": NumSamplesDataset(),
            "ntokens": NumelDataset(base, reduce=True),
        }
    )
    assert len(ds) == 2
    batch = ds.collater([ds[0], ds[1]])
    assert batch["net_input"]["src_tokens"].shape == (2, 2)
    assert batch["nsamples"] == 2
    assert batch["ntokens"] == 4


def test_nested_prefetch_dedupes_shared_leaf_store():
    """One batch's prefetch fan-out must hit each LEAF STORE once, even
    when several nested leaves (e.g. mask-tokens src/tgt twins) wrap the
    same store — and stores that are genuinely different must all be hit.
    Per-call dedup via ``prefetch_target``: a cross-call key on the store
    is defeated by worker threads interleaving different batches."""

    class Store(ListDataset):
        supports_prefetch = True

        def __init__(self, items):
            super().__init__(items)
            self.calls = []

        def prefetch(self, indices):
            self.calls.append(list(indices))

    store = Store([np.array([1, 2]), np.array([3, 4])])
    other = Store([np.array([5, 6]), np.array([7, 8])])
    ds = NestedDictionaryDataset(
        {
            "net_input": {
                "src_tokens": RightPadDataset(store, pad_idx=0,
                                              pad_to_multiple=1)
            },
            "target": RightPadDataset(store, pad_idx=0, pad_to_multiple=1),
            "aux": other,
        }
    )
    assert ds.supports_prefetch
    ds.prefetch([0, 1])
    assert store.calls == [[0, 1]]  # shared store: exactly once
    assert other.calls == [[0, 1]]  # distinct store: still reached
    ds.prefetch([1])  # a different batch is a fresh fan-out
    assert store.calls == [[0, 1], [1]]


def test_token_wrappers():
    base = ListDataset([np.array([5, 6], dtype=np.int64)])
    ds = AppendTokenDataset(PrependTokenDataset(base, 0), 2)
    np.testing.assert_array_equal(ds[0], [0, 5, 6, 2])

    d = make_dictionary()
    raw = ListDataset([np.array(["a", "b"])])
    tok = TokenizeDataset(raw, d, max_seq_len=16)
    np.testing.assert_array_equal(tok[0], [5, 6])


def test_sort_and_epoch_shuffle():
    base = ListDataset([np.array([i]) for i in range(10)])
    lengths = np.array([5, 3, 8, 1, 9, 2, 7, 0, 6, 4])
    ds = SortDataset(base, sort_order=[lengths])
    np.testing.assert_array_equal(lengths[ds.ordered_indices()], np.arange(10))

    sh = EpochShuffleDataset(base, seed=3)
    sh.set_epoch(1)
    o1 = sh.ordered_indices().copy()
    sh.set_epoch(2)
    o2 = sh.ordered_indices().copy()
    assert not np.array_equal(o1, o2)
    sh.set_epoch(1)
    np.testing.assert_array_equal(sh.ordered_indices(), o1)


def test_batch_by_size_multiple():
    batches = data_utils.batch_by_size(np.arange(10), batch_size=3, required_batch_size_multiple=4)
    assert [len(b) for b in batches] == [4, 4, 2]


class _Collate:
    def __call__(self, samples):
        return np.stack(samples)


def make_epoch_iterator(n=12, num_shards=1, shard_id=0, batch=2, buffer_size=0):
    base = ListDataset([np.array([i]) for i in range(n)])
    sampler = data_utils.batch_by_size(np.arange(n), batch_size=batch)
    return iterators.EpochBatchIterator(
        dataset=base,
        collate_fn=base.collater,
        batch_sampler=sampler,
        seed=1,
        num_shards=num_shards,
        shard_id=shard_id,
        buffer_size=buffer_size,
    )


def test_epoch_batch_iterator_basic():
    it = make_epoch_iterator()
    epoch_itr = it.next_epoch_itr(shuffle=False)
    batches = list(epoch_itr)
    assert len(batches) == 6
    np.testing.assert_array_equal(batches[0], [[0], [1]])
    assert it.end_of_epoch()
    assert it.next_epoch_idx == 2


def test_epoch_batch_iterator_shuffle_deterministic():
    it1 = make_epoch_iterator()
    it2 = make_epoch_iterator()
    b1 = [b.tolist() for b in it1.next_epoch_itr(shuffle=True)]
    b2 = [b.tolist() for b in it2.next_epoch_itr(shuffle=True)]
    assert b1 == b2  # same seed+epoch -> same order


def test_epoch_iterator_sharding_lockstep():
    # 5 batches over 2 shards: shard 1 gets padded with an empty batch
    it0 = make_epoch_iterator(n=10, num_shards=2, shard_id=0)
    it1 = make_epoch_iterator(n=10, num_shards=2, shard_id=1)
    b0 = list(it0.next_epoch_itr(shuffle=False))
    b1 = list(it1.next_epoch_itr(shuffle=False))
    assert len(b0) == len(b1) == 3
    assert isinstance(b1[-1], dict) and len(b1[-1]) == 0  # dummy batch


def test_epoch_iterator_resume_mid_epoch():
    it = make_epoch_iterator()
    epoch_itr = it.next_epoch_itr(shuffle=False)
    consumed = [next(epoch_itr), next(epoch_itr)]
    state = it.state_dict()
    assert state["iterations_in_epoch"] == 2

    it2 = make_epoch_iterator()
    it2.load_state_dict(state)
    resumed = list(it2.next_epoch_itr(shuffle=False))
    assert len(resumed) == 4
    np.testing.assert_array_equal(resumed[0], [[4], [5]])


def test_epoch_iterator_end_of_epoch_state():
    it = make_epoch_iterator()
    list(it.next_epoch_itr(shuffle=False))
    state = it.state_dict()
    assert state["epoch"] == 2 and state["iterations_in_epoch"] == 0


def test_grouped_iterator():
    it = make_epoch_iterator()
    epoch_itr = it.next_epoch_itr(shuffle=False)
    groups = list(iterators.GroupedIterator(epoch_itr, 4))
    assert [len(g) for g in groups] == [4, 2]


def test_buffered_iterator():
    it = make_epoch_iterator(buffer_size=4)
    batches = list(it.next_epoch_itr(shuffle=False))
    assert len(batches) == 6


def test_counting_iterator_skip_take():
    itr = iterators.CountingIterator(iter(range(10)), total=10)
    itr.skip(3)
    assert itr.n == 3
    itr.take(5)
    assert list(itr) == [3, 4]


def test_process_worker_pool_matches_thread():
    """--worker-impl process: forked worker processes produce the identical
    batch stream (order and content) as threads and as no workers."""
    n, batch = 12, 2
    base = ListDataset([np.array([i]) for i in range(n)])
    sampler = data_utils.batch_by_size(np.arange(n), batch_size=batch)

    def run():
        it = iterators.EpochBatchIterator(
            dataset=base, collate_fn=base.collater, batch_sampler=sampler,
            seed=1, num_workers=2,
        )
        return [b.tolist() for b in it.next_epoch_itr(shuffle=True)]

    baseline = run()
    iterators.set_worker_impl("process")
    try:
        assert run() == baseline
    finally:
        iterators.set_worker_impl("thread")


def test_process_worker_resume_sees_current_epoch():
    """Resume with --worker-impl process: the worker fork happens AFTER
    set_epoch, so epoch-dependent datasets collate with the resumed epoch
    (regression: workers were forked with stale epoch-1 state)."""

    class EpochEcho(ListDataset):
        def __init__(self, n):
            super().__init__([np.array([0])] * n)
            self.epoch = 1

        def set_epoch(self, epoch):
            self.epoch = epoch

        def __getitem__(self, idx):
            return np.array([self.epoch * 100 + idx])

    def build():
        ds = EpochEcho(8)
        return ds, iterators.EpochBatchIterator(
            dataset=ds, collate_fn=ds.collater,
            batch_sampler=data_utils.batch_by_size(np.arange(8), batch_size=2),
            seed=1, num_workers=2, epoch=3,
        )

    iterators.set_worker_impl("process")
    try:
        _, it1 = build()
        epoch_itr = it1.next_epoch_itr(shuffle=False)
        next(epoch_itr)  # consume one batch -> mid-epoch
        state = it1.state_dict()

        _, it2 = build()
        it2.load_state_dict(state)
        batch = next(it2.next_epoch_itr(shuffle=False))
        # values are epoch*100 + idx: must reflect epoch 3, not a stale 1
        assert all(300 <= v < 400 for v in np.asarray(batch).ravel()), batch
    finally:
        iterators.set_worker_impl("thread")


def test_record_dataset_fallback_without_native(tmp_path, monkeypatch):
    """The mmap fallback branch of read_batch/prefetch (native extension
    absent) — always runs, independent of whether the extension is built."""
    from unicore_tpu.data import IndexedRecordWriter
    from unicore_tpu.data import indexed_dataset as mod

    path = str(tmp_path / "d.rec")
    with IndexedRecordWriter(path) as w:
        for i in range(6):
            w.write({"v": np.array([i, i + 1])})
    monkeypatch.setattr(mod, "_native", None)
    ds = mod.IndexedRecordDataset(path)
    assert not ds.supports_prefetch
    ds.prefetch(range(6))  # no-op, must not raise
    got = ds.read_batch(np.array([4, 0]))
    np.testing.assert_array_equal(got[0]["v"], [4, 5])
    np.testing.assert_array_equal(got[1]["v"], [0, 1])
