"""BertModel slot-head ↔ MaskedLMLoss contract (VERDICT r2 item 2).

The static-capacity masked-token-only LM head returns
``{logits, slot_index, slot_valid}``; the loss must produce the SAME loss
and sample_size as the full ``[B, T, V]`` projection when every masked
position fits in the K slots, and on overflow must drop the excess from
both the numerator and the denominator (``sample_size = sum(slot_valid)``).
Reference semantics being matched: ``examples/bert/model.py:183-194`` +
``unicore/losses/masked_lm.py:19-36``.
"""

from types import SimpleNamespace

import jax
import numpy as np
import pytest

from examples.bert.model import BertModel
from unicore_tpu.losses.masked_lm import MaskedLMLoss

VOCAB, PAD = 32, 0


def make_model(capacity):
    return BertModel(
        vocab_size=VOCAB, padding_idx=PAD, encoder_layers=1,
        encoder_embed_dim=32, encoder_ffn_embed_dim=64,
        encoder_attention_heads=2, emb_dropout=0.0, dropout=0.0,
        attention_dropout=0.0, activation_dropout=0.0, max_seq_len=256,
        masked_loss_capacity=capacity,
    )


def build_loss():
    task = SimpleNamespace(
        dictionary=SimpleNamespace(pad=lambda: PAD), args=SimpleNamespace()
    )
    loss = MaskedLMLoss.__new__(MaskedLMLoss)
    loss.task = task
    loss.padding_idx = PAD
    return loss


def make_sample(rng, bsz, seq, n_masked):
    toks = rng.randint(4, VOCAB, size=(bsz, seq)).astype(np.int64)
    target = np.full((bsz, seq), PAD, dtype=np.int64)
    flat = target.reshape(-1)
    pick = rng.choice(bsz * seq, size=n_masked, replace=False)
    flat[pick] = rng.randint(4, VOCAB, size=n_masked)
    return {"net_input": {"src_tokens": toks}, "target": target}


def run(model, sample):
    params = model.init(
        jax.random.PRNGKey(0),
        sample["net_input"]["src_tokens"],
        masked_tokens=(sample["target"] != PAD),
    )["params"]
    loss_fn = build_loss()
    return params, loss_fn.forward(model, params, sample, is_training=False)


def test_slot_head_matches_full_projection(rng):
    """No overflow: slot-head loss == full-projection loss (same params)."""
    sample = make_sample(rng, bsz=2, seq=64, n_masked=20)
    slot_model = make_model(0.25)
    full_model = make_model(0.0)
    # identical param trees: the lm_head modules are the same, only the
    # gather differs — init once, evaluate both
    params, (l_slot, n_slot, log_slot) = run(slot_model, sample)
    l_full, n_full, log_full = build_loss().forward(
        full_model, params, sample, is_training=False
    )
    assert float(n_slot) == float(n_full) == 20
    np.testing.assert_allclose(float(l_slot), float(l_full), rtol=1e-5)
    np.testing.assert_allclose(
        float(log_slot["loss"]), float(log_full["loss"]), rtol=1e-5
    )


def test_slot_head_overflow_drops_consistently(rng):
    """More masked positions than K slots: the excess is dropped from loss
    AND sample_size (normalization stays exact), and the kept slots are the
    lowest flat indices (top_k tie resolution)."""
    bsz, seq, n_masked = 2, 128, 160  # K = ceil(0.25*256 -> 64 /128)*128 = 128
    sample = make_sample(rng, bsz=bsz, seq=seq, n_masked=n_masked)
    slot_model = make_model(0.25)
    params, (l_slot, n_slot, _) = run(slot_model, sample)
    assert float(n_slot) == 128  # sum(slot_valid), not the full masked count

    # oracle: full projection restricted to the first 128 masked flat indices
    full_model = make_model(0.0)
    logits = full_model.apply(
        {"params": params}, sample["net_input"]["src_tokens"],
        deterministic=True,
    )
    lp = jax.nn.log_softmax(np.asarray(logits, dtype=np.float64), axis=-1)
    flat_t = sample["target"].reshape(-1)
    masked_idx = np.nonzero(flat_t != PAD)[0][:128]
    lp2 = lp.reshape(bsz * seq, VOCAB)
    expect = -lp2[masked_idx, flat_t[masked_idx]].sum()
    np.testing.assert_allclose(float(l_slot), expect, rtol=1e-4)


def test_full_projection_counts_all_masked(rng):
    sample = make_sample(rng, bsz=2, seq=64, n_masked=30)
    full_model = make_model(0.0)
    _, (_, n, log) = run(full_model, sample)
    assert float(n) == 30
    assert float(log["sample_size"]) == 30
