"""Deploy tier (unicore_tpu/deploy): train-to-serve continuous
deployment — verified manifest publish, zero-downtime hot-swap, and
canary-gated rollout.

The load-bearing properties:

- a manifest inherits the checkpoint integrity ladder (marker-last
  atomic write, torn-write discrimination, monotonic publish ids);
- ``swap_weights`` installs new params BETWEEN serve steps without
  touching the paged-KV pool, page tables, or in-flight sequences —
  a same-weights swap mid-generation is bit-invisible;
- the rollout state machine promotes only through a gated canary, and
  a poisoned or torn publish never reaches a second replica."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from examples.lm.model import TransformerLMModel
from unicore_tpu.checkpoint_utils import (CheckpointIntegrityError,
                                          atomic_save, file_integrity)
from unicore_tpu.deploy import (DeployError, DeploySubscriber,
                                RolloutController, WeightPublisher,
                                load_manifest_params, manifest_name,
                                read_manifest, scan_publish_dir)
from unicore_tpu.fleet import FleetRouter, clip_trace, generate_trace, \
    replay_trace
from unicore_tpu.serve.engine import ServeEngine, WeightSwapError
from unicore_tpu.serve.scheduler import Request

V, PAD = 29, 0
POOL = dict(num_pages=24, page_size=4, max_batch=4)
MAX_CONTEXT = (POOL["num_pages"] - 1) * POOL["page_size"]


@pytest.fixture(scope="module")
def lm():
    model = TransformerLMModel(
        vocab_size=V, padding_idx=PAD, decoder_layers=2,
        decoder_embed_dim=32, decoder_ffn_embed_dim=64,
        decoder_attention_heads=4, max_seq_len=64,
        emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, rel_pos=False, abs_pos=False, rotary=True,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def save_checkpoint_for(params, path, *, poison=False):
    host = jax.device_get(params)
    if poison:
        host = jax.tree_util.tree_map(
            lambda x: np.full_like(np.asarray(x), np.nan), host)
    atomic_save({"model": {"params": host}, "args": None}, path)
    return path


def solo_tokens(lm, req):
    model, params = lm
    engine = ServeEngine(model, params, num_pages=64, page_size=4,
                         max_batch=1)
    [res] = engine.generate([dataclasses.replace(req)])
    return res.tokens


# -- publisher: manifest atomicity, versioning, torn discrimination ---------


def test_publish_writes_versioned_verified_manifest(lm, tmp_path):
    _, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub = WeightPublisher(str(tmp_path / "publish"))
    m = pub.publish(ckpt, source_step=7)
    assert m.publish_id == 1 and m.source_step == 7
    assert m.checkpoint == os.path.abspath(ckpt)
    assert os.path.basename(ckpt) in m.sha256
    # marker-last atomic write: the manifest verifies like a checkpoint
    path = tmp_path / "publish" / manifest_name(1)
    assert file_integrity(str(path)) == "ok"
    again = read_manifest(str(path))
    assert again == m


def test_publish_ids_are_monotonic_and_recovered(lm, tmp_path):
    _, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub = WeightPublisher(str(tmp_path / "publish"))
    assert pub.publish(ckpt).publish_id == 1
    assert pub.publish(ckpt).publish_id == 2
    # a fresh publisher (post-restart) continues the sequence from disk
    pub2 = WeightPublisher(str(tmp_path / "publish"))
    assert pub2.publish(ckpt).publish_id == 3


def test_publish_refuses_torn_checkpoint(lm, tmp_path):
    _, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    with open(ckpt, "r+b") as fh:
        fh.write(b"torn!")
    pub = WeightPublisher(str(tmp_path / "publish"))
    with pytest.raises(CheckpointIntegrityError):
        pub.publish(ckpt)
    assert scan_publish_dir(str(tmp_path / "publish")) == {}


def test_torn_manifest_discriminated_and_skipped(lm, tmp_path):
    _, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub_dir = str(tmp_path / "publish")
    pub = WeightPublisher(pub_dir)
    pub.publish(ckpt)
    m2 = pub.publish(ckpt)
    # tear the NEWER manifest after its marker landed
    with open(os.path.join(pub_dir, manifest_name(m2.publish_id)),
              "r+b") as fh:
        fh.write(b"torn!")
    states = {pid: st for pid, (_, st) in scan_publish_dir(pub_dir).items()}
    assert states == {1: "ok", 2: "torn"}
    with pytest.raises(CheckpointIntegrityError):
        read_manifest(os.path.join(pub_dir, manifest_name(2)))
    sub = DeploySubscriber(pub_dir)
    m = sub.poll()
    assert m is not None and m.publish_id == 1
    torn = sub.take_torn()
    assert [pid for pid, _ in torn] == [2]
    assert sub.take_torn() == []  # reported once, not every poll


def test_unverified_manifest_held_until_marker_lands(lm, tmp_path):
    """A manifest whose .sum has not landed yet is an IN-FLIGHT write:
    the subscriber must neither surface nor condemn it."""
    _, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub_dir = str(tmp_path / "publish")
    pub = WeightPublisher(pub_dir)
    pub.publish(ckpt)
    path = os.path.join(pub_dir, manifest_name(1))
    os.rename(path + ".sum", path + ".sum.hold")
    sub = DeploySubscriber(pub_dir)
    assert sub.poll() is None
    assert sub.take_torn() == []
    os.rename(path + ".sum.hold", path + ".sum")
    m = sub.poll()
    assert m is not None and m.publish_id == 1


def test_subscriber_is_deterministic_and_rate_limited(lm, tmp_path):
    _, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub_dir = str(tmp_path / "publish")
    pub = WeightPublisher(pub_dir)
    pub.publish(ckpt)
    pub.publish(ckpt)
    # two independent subscribers surface the SAME newest manifest
    a, b = DeploySubscriber(pub_dir), DeploySubscriber(pub_dir)
    ma, mb = a.poll(), b.poll()
    assert ma == mb and ma.publish_id == 2
    assert a.poll() is None  # nothing new
    # injectable clock: polls inside min_interval_s do not touch disk
    now = {"t": 100.0}
    c = DeploySubscriber(pub_dir, min_interval_s=5.0,
                         clock=lambda: now["t"])
    assert c.poll().publish_id == 2
    pub.publish(ckpt)
    assert c.poll() is None  # rate-limited, not yet due
    now["t"] += 6.0
    assert c.poll().publish_id == 3


def test_manifest_digest_drift_refused(lm, tmp_path):
    """A checkpoint silently REPLACED after its manifest landed must not
    load: the manifest pins the digest recorded at publish time."""
    _, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub = WeightPublisher(str(tmp_path / "publish"))
    m = pub.publish(ckpt)
    # replace with a VALID (atomic_save'd) but different checkpoint
    save_checkpoint_for(
        jax.tree_util.tree_map(lambda x: x * 2.0, params), ckpt)
    with pytest.raises(CheckpointIntegrityError):
        load_manifest_params(m)


def test_loader_refuses_checkpoint_without_params(tmp_path):
    path = str(tmp_path / "checkpoint_x.pt")
    atomic_save({"model": {"step": 3}, "args": None}, path)
    pub = WeightPublisher(str(tmp_path / "publish"))
    m = pub.publish(path)
    with pytest.raises(DeployError):
        load_manifest_params(m)


# -- hot-swap: in-flight sequences, pool, page tables survive ---------------


def _drive(engine, requests, *, swap_at=None, swap_params=None):
    """Step the engine to completion, optionally hot-swapping at a step
    boundary mid-flight; returns ({request_id: tokens}, swap_stall)."""
    engine.submit([dataclasses.replace(r) for r in requests])
    finished, steps, stall = [], 0, None
    while engine.has_work():
        engine.serve_step()
        finished.extend(engine.collect_finished())
        steps += 1
        if swap_at is not None and steps == swap_at:
            assert engine.has_work(), "swap must land mid-flight"
            stall = engine.swap_weights(swap_params)
        assert steps < 500
    finished.extend(engine.collect_finished())
    return {r.request_id: r.tokens for r in finished}, stall


def test_swap_mid_flight_is_bit_invisible(lm):
    """Same-weights swap between serve steps: every stream — including
    the ones in flight across the boundary — matches the no-swap run
    bit-exactly, and the pool object/pages are untouched."""
    model, params = lm
    reqs = [Request(prompt=[1 + (i * 3) % (V - 1)] * (4 + i),
                    max_new_tokens=10, seed=i, request_id=f"q{i}")
            for i in range(6)]
    baseline, _ = _drive(ServeEngine(model, params, **POOL), reqs)
    eng = ServeEngine(model, params, **POOL)
    pool_before = eng.pool
    swapped, stall = _drive(eng, reqs, swap_at=3,
                            swap_params=jax.device_get(params))
    assert swapped == baseline
    assert eng.pool is pool_before  # the pool survived, not rebuilt
    assert eng.pool.is_idle()
    eng.pool.check_invariants()
    assert eng.weight_swaps == 1 and stall >= 0.0


def test_swap_rejects_mismatched_trees(lm):
    model, params = lm
    eng = ServeEngine(model, params, **POOL)
    host = jax.device_get(params)
    with pytest.raises(WeightSwapError):
        eng.swap_weights({"decoder": {}})  # different structure
    bad_shape = jax.tree_util.tree_map(
        lambda x: np.zeros(tuple(s + 1 for s in np.shape(x)),
                           np.asarray(x).dtype), host)
    with pytest.raises(WeightSwapError):
        eng.swap_weights(bad_shape)
    bad_dtype = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float16), host)
    with pytest.raises(WeightSwapError):
        eng.swap_weights(bad_dtype)
    # a failed swap leaves the engine serving: no partial install
    assert eng.weight_swaps == 0
    [res] = eng.generate([Request(prompt=[1, 2], max_new_tokens=4,
                                  seed=0, request_id="after")])
    assert res.finish_reason in ("eos", "length")


def test_swap_donation_spares_shared_boot_params(lm):
    """Boot params may be SHARED across in-process replicas: the first
    swap must not delete them (engine B keeps serving), while a later
    swap deletes the buffers the engine itself installed."""
    model, params = lm
    host = jax.device_get(params)
    a = ServeEngine(model, params, **POOL)
    b = ServeEngine(model, params, **POOL)  # same params tree object
    a.swap_weights(host)
    installed = a.params
    [res] = b.generate([Request(prompt=[1, 2, 3], max_new_tokens=4,
                                seed=0, request_id="b0")])
    assert res.finish_reason in ("eos", "length")  # boot buffers alive
    a.swap_weights(host)
    deleted = [leaf.is_deleted()
               for leaf in jax.tree_util.tree_leaves(installed)
               if isinstance(leaf, jax.Array)]
    assert deleted and all(deleted)  # owned buffers donated on re-swap
    [res] = a.generate([Request(prompt=[1, 2, 3], max_new_tokens=4,
                                seed=0, request_id="a0")])
    assert res.finish_reason in ("eos", "length")


# -- canary rollout state machine -------------------------------------------


def _fleet_with_rollout(lm, pub_dir, **ctl_kw):
    model, params = lm
    engines = {f"r{i}": ServeEngine(model, params, **POOL)
               for i in range(2)}
    router = FleetRouter(engines)
    kw = dict(canary_steps=8, divert_period=4, seed=0)
    kw.update(ctl_kw)
    ctl = RolloutController(router, DeploySubscriber(pub_dir), **kw)
    return router, engines, ctl


def _trace(n=24, seed=1106):
    return clip_trace(generate_trace(seed, num_requests=n, vocab=V - 1),
                      MAX_CONTEXT)


def test_canary_promotes_good_manifest_fleet_wide(lm, tmp_path):
    model, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub_dir = str(tmp_path / "publish")
    WeightPublisher(pub_dir).publish(ckpt, source_step=11)
    router, engines, ctl = _fleet_with_rollout(lm, pub_dir)
    trace = _trace()
    replay_trace(router, trace)
    results = router.results()
    assert ctl.state == "idle"
    assert ctl.stats["promotes"] == 1 and ctl.stats["rollbacks"] == 0
    assert ctl.current.publish_id == 1 and ctl.current.source_step == 11
    assert {r: engines[r].weight_swaps
            for r in sorted(engines)} == {"r0": 1, "r1": 1}
    # zero-drop: every admitted request finished, solo-oracle exact
    for ev in trace:
        res = results[ev.request.request_id]
        assert res.finish_reason in ("eos", "length")
        assert res.tokens == solo_tokens(lm, ev.request)
    assert router.fleet_report()["deploy"]["current"] == 1
    # the ring healed: canary rejoined after its window
    assert sorted(router.ring.members()) == ["r0", "r1"]


def test_canary_rolls_back_nan_manifest_before_second_replica(lm, tmp_path):
    model, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"),
                               poison=True)
    pub_dir = str(tmp_path / "publish")
    WeightPublisher(pub_dir).publish(ckpt)
    router, engines, ctl = _fleet_with_rollout(lm, pub_dir)
    replay_trace(router, _trace())
    assert ctl.state == "idle" and ctl.current is None
    assert ctl.stats["rollbacks"] == 1 and ctl.stats["promotes"] == 0
    assert 1 in ctl.quarantined
    assert ctl.breaker.state == "open"
    # swap + rollback on the canary; the poison NEVER reached r1
    assert engines["r0"].weight_swaps == 2
    assert engines["r1"].weight_swaps == 0
    # post-rollback the canary serves the restored weights
    req = Request(prompt=[1, 2, 3], max_new_tokens=6, seed=0,
                  request_id="post")
    [res] = engines["r0"].generate([dataclasses.replace(req)])
    assert res.tokens == solo_tokens(lm, req)


def test_rollback_restores_prior_promoted_manifest(lm, tmp_path):
    """Good m1 promotes; NaN m2 rolls back — current must STAY m1 and
    the canary must serve m1's weights again."""
    model, params = lm
    good = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    bad = save_checkpoint_for(params, str(tmp_path / "checkpoint_2.pt"),
                              poison=True)
    pub_dir = str(tmp_path / "publish")
    pub = WeightPublisher(pub_dir)
    pub.publish(good, source_step=10)
    router, engines, ctl = _fleet_with_rollout(lm, pub_dir)
    replay_trace(router, _trace())
    assert ctl.current.publish_id == 1
    pub.publish(bad, source_step=20)
    # breaker is CLOSED (m1 promoted cleanly): m2 canaries immediately
    replay_trace(router, _trace(seed=1107))
    assert ctl.current.publish_id == 1  # m1 survived m2's rollback
    assert ctl.quarantined and 2 in ctl.quarantined
    assert ctl.stats["promotes"] == 1 and ctl.stats["rollbacks"] == 1
    assert engines["r1"].weight_swaps == 1  # m1 promote only
    req = Request(prompt=[2, 4, 6], max_new_tokens=6, seed=1,
                  request_id="post2")
    [res] = engines["r0"].generate([dataclasses.replace(req)])
    assert res.tokens == solo_tokens(lm, req)


def test_torn_manifest_condemned_without_any_swap(lm, tmp_path):
    model, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))
    pub_dir = str(tmp_path / "publish")
    pub = WeightPublisher(pub_dir)
    m = pub.publish(ckpt)
    with open(os.path.join(pub_dir, manifest_name(m.publish_id)),
              "r+b") as fh:
        fh.write(b"torn!")
    router, engines, ctl = _fleet_with_rollout(lm, pub_dir)
    replay_trace(router, _trace(8))
    assert 1 in ctl.quarantined and "torn" in ctl.quarantined[1]
    assert ctl.breaker.state == "open"
    assert all(e.weight_swaps == 0 for e in engines.values())


def test_rollout_replay_is_deterministic(lm, tmp_path):
    model, params = lm
    ckpt = save_checkpoint_for(params, str(tmp_path / "checkpoint_1.pt"))

    def run(tag):
        pub_dir = str(tmp_path / f"publish_{tag}")
        WeightPublisher(pub_dir).publish(ckpt)
        router, engines, ctl = _fleet_with_rollout(lm, pub_dir)
        trace = _trace()
        replay_trace(router, trace)
        results = router.results()
        return ({e.request.request_id: results[e.request.request_id].tokens
                 for e in trace if e.request.request_id in results},
                dict(ctl.stats),
                [h["step"] for h in ctl.history])

    assert run("a") == run("b")
