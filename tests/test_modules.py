"""Tests for the flax NN modules (attention, encoder, decoder).

Oracles: torch CPU compositions for numerics (same pattern as the reference's
``tests/test_softmax.py``), plus behavioral invariants (causality, padding
invariance) that the reference never tested but which pin the contracts."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from unicore_tpu.modules import (
    CrossMultiheadAttention,
    SelfMultiheadAttention,
    TransformerDecoder,
    TransformerEncoder,
    relative_position_bucket,
)


def test_relative_position_bucket_matches_torch_formula():
    # independent torch reimplementation of the T5 bucketing from the paper
    rel = np.arange(-300, 300, dtype=np.int64)
    ours = np.asarray(relative_position_bucket(rel, num_buckets=32, max_distance=128))

    t = torch.from_numpy(rel)
    sign = torch.sign(t)
    num_buckets = 16
    n = torch.abs(t)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    max_bucket_val = num_buckets - 1 - max_exact
    val_if_large = max_exact + torch.ceil(
        torch.log(n.float() / max_exact) / np.log((128 - 1) / max_exact) * max_bucket_val
    ).long()
    val_if_large = torch.min(val_if_large, torch.full_like(val_if_large, num_buckets - 1))
    ref = (torch.where(is_small, n, val_if_large) * sign).numpy()
    np.testing.assert_array_equal(ours, ref)


def test_rotary_scores_depend_only_on_relative_offset(rng):
    """RoPE's defining property: q_i . k_j after rotation is a function
    of (i - j) only — shifting both positions by the same amount leaves
    every score unchanged."""
    from unicore_tpu.modules import apply_rotary, rotary_cos_sin

    B, T, H, D = 1, 16, 2, 32
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    pos0 = jnp.arange(T, dtype=jnp.float32)
    shift = 37.0
    # HIGHEST precision: on TPU the default einsum is single-pass bf16
    # (~0.07 abs noise here), which would drown the property under test
    for pos in (pos0, pos0 + shift):
        cos, sin = rotary_cos_sin(T, D, positions=pos)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", apply_rotary(q, cos, sin),
            apply_rotary(k, cos, sin),
            precision=jax.lax.Precision.HIGHEST,
        )
        if pos is pos0:
            s_base = s
    np.testing.assert_allclose(np.asarray(s_base), np.asarray(s),
                               rtol=1e-4, atol=1e-4)
    # rotation preserves per-vector norms
    cos, sin = rotary_cos_sin(T, D)
    qr = apply_rotary(q, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-5,
    )
    # and genuinely changes non-zero-offset scores
    assert np.abs(np.asarray(s_base) - np.asarray(
        jnp.einsum("bqhd,bkhd->bhqk", q, k)
    )).max() > 1e-2


def test_decoder_rotary_trains_and_differs_from_absolute(rng):
    """TransformerDecoder(rotary=True) runs fwd+bwd with finite grads and
    produces different outputs than the non-rotary stack (same params)."""
    from unicore_tpu.modules import TransformerDecoder

    x = jnp.asarray(rng.randn(2, 32, 64).astype(np.float32))
    kw = dict(decoder_layers=1, embed_dim=64, ffn_embed_dim=128,
              attention_heads=2, max_seq_len=32, rel_pos=False,
              emb_dropout=0.0, dropout=0.0, attention_dropout=0.0)
    dec_r = TransformerDecoder(rotary=True, **kw)
    dec_a = TransformerDecoder(rotary=False, **kw)
    params = dec_r.init(jax.random.PRNGKey(0), x)["params"]

    def loss(p):
        return jnp.sum(dec_r.apply({"params": p}, x) ** 2)

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
    o_r = dec_r.apply({"params": params}, x)
    o_a = dec_a.apply({"params": params}, x)  # same param tree shape
    # bert-init weights give near-uniform attention, so the positional
    # signal is small but must be present
    assert np.abs(np.asarray(o_r) - np.asarray(o_a)).max() > 1e-4


def test_decoder_checkpoint_activations_matches(rng):
    """Remat must change memory, not math: same outputs and grads with
    checkpoint_activations on and off."""
    from unicore_tpu.modules import TransformerDecoder

    x = jnp.asarray(rng.randn(2, 32, 64).astype(np.float32))
    kw = dict(decoder_layers=2, embed_dim=64, ffn_embed_dim=128,
              attention_heads=2, max_seq_len=32,
              emb_dropout=0.0, dropout=0.0, attention_dropout=0.0)
    dec = TransformerDecoder(checkpoint_activations=False, **kw)
    dec_r = TransformerDecoder(checkpoint_activations=True, **kw)
    params = dec.init(jax.random.PRNGKey(0), x)["params"]

    def loss(mod, p):
        return jnp.sum(mod.apply({"params": p}, x) ** 2)

    l0, g0 = jax.value_and_grad(lambda p: loss(dec, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(dec_r, p))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        ),
        g0, g1,
    )


def test_self_attention_matches_torch(rng):
    B, T, E, H = 2, 10, 32, 4
    x = rng.randn(B, T, E).astype(np.float32)
    attn = SelfMultiheadAttention(embed_dim=E, num_heads=H, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), jnp.asarray(x))
    out = attn.apply(params, jnp.asarray(x))

    # reassemble with torch from the same weights
    p = params["params"]
    # DenseGeneral kernel [E, 3, H, Dh] == the reference's [E, 3E] layout
    w_in = np.asarray(p["in_proj"]["kernel"]).reshape(E, 3 * E)
    b_in = np.asarray(p["in_proj"]["bias"]).reshape(3 * E)
    w_out = np.asarray(p["out_proj"]["kernel"])
    b_out = np.asarray(p["out_proj"]["bias"])

    tx = torch.from_numpy(x)
    qkv = tx @ torch.from_numpy(w_in) + torch.from_numpy(b_in)
    # our layout: [B, T, 3, H, D]
    qkv = qkv.view(B, T, 3, H, E // H)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = q.permute(0, 2, 1, 3) * (E // H) ** -0.5
    k = k.permute(0, 2, 1, 3)
    v = v.permute(0, 2, 1, 3)
    probs = torch.softmax(q @ k.transpose(-1, -2), dim=-1)
    o = (probs @ v).permute(0, 2, 1, 3).reshape(B, T, E)
    ref = (o @ torch.from_numpy(w_out) + torch.from_numpy(b_out)).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4)


def test_self_attention_padding_mask(rng):
    """Padded key positions must not influence unpadded outputs."""
    B, T, E, H = 2, 8, 16, 2
    x = rng.randn(B, T, E).astype(np.float32)
    pad = np.zeros((B, T), dtype=np.int32)
    pad[:, 6:] = 1  # last 2 positions padded

    attn = SelfMultiheadAttention(embed_dim=E, num_heads=H, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), jnp.asarray(x))

    out1 = attn.apply(params, jnp.asarray(x), key_padding_mask=jnp.asarray(pad))
    x2 = x.copy()
    x2[:, 6:] = 123.0  # garbage in padded keys
    out2 = attn.apply(params, jnp.asarray(x2), key_padding_mask=jnp.asarray(pad))
    np.testing.assert_allclose(
        np.asarray(out1)[:, :6], np.asarray(out2)[:, :6], atol=1e-5
    )


def test_self_attention_bias_reference_convention(rng):
    """[B*H, q, k] bias (reference convention) == [B, H, q, k] bias."""
    B, T, E, H = 2, 6, 16, 2
    x = jnp.asarray(rng.randn(B, T, E).astype(np.float32))
    bias4 = rng.randn(B, H, T, T).astype(np.float32)
    attn = SelfMultiheadAttention(embed_dim=E, num_heads=H, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), x)
    o4 = attn.apply(params, x, attn_bias=jnp.asarray(bias4))
    o3 = attn.apply(params, x, attn_bias=jnp.asarray(bias4.reshape(B * H, T, T)))
    np.testing.assert_allclose(np.asarray(o4), np.asarray(o3), atol=1e-6)


def test_cross_attention_shapes(rng):
    B, Tq, Tk, E, H = 2, 5, 9, 16, 4
    q = jnp.asarray(rng.randn(B, Tq, E).astype(np.float32))
    kv = jnp.asarray(rng.randn(B, Tk, E).astype(np.float32))
    attn = CrossMultiheadAttention(embed_dim=E, num_heads=H, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), q, kv, kv)
    out = attn.apply(params, q, kv, kv)
    assert out.shape == (B, Tq, E)


def test_encoder_runs_and_grads_flow(rng):
    B, T, E = 2, 12, 32
    enc = TransformerEncoder(
        encoder_layers=2, embed_dim=E, ffn_embed_dim=64, attention_heads=4,
        max_seq_len=16, emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
    )
    emb = jnp.asarray(rng.randn(B, T, E).astype(np.float32))
    params = enc.init(jax.random.PRNGKey(0), emb)
    out = enc.apply(params, emb)
    assert out.shape == (B, T, E)

    def loss_fn(p):
        return jnp.sum(enc.apply(p, emb) ** 2)

    grads = jax.grad(loss_fn)(params)
    gnorm = sum(
        float(jnp.sum(jnp.abs(g))) for g in jax.tree_util.tree_leaves(grads)
    )
    assert np.isfinite(gnorm) and gnorm > 0
    # rel-pos bias gets gradient
    g_rel = grads["params"]["relative_attention_bias"]["weight"]
    assert float(jnp.sum(jnp.abs(g_rel))) > 0


def test_encoder_padding_invariance(rng):
    B, T, E = 2, 10, 32
    enc = TransformerEncoder(
        encoder_layers=2, embed_dim=E, ffn_embed_dim=64, attention_heads=4,
        max_seq_len=16, emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
    )
    x = rng.randn(B, T, E).astype(np.float32)
    pad = np.zeros((B, T), dtype=np.int32)
    pad[:, 7:] = 1
    params = enc.init(jax.random.PRNGKey(0), jnp.asarray(x))
    o1 = enc.apply(params, jnp.asarray(x), padding_mask=jnp.asarray(pad))
    x2 = x.copy()
    x2[:, 7:] = -55.0
    o2 = enc.apply(params, jnp.asarray(x2), padding_mask=jnp.asarray(pad))
    np.testing.assert_allclose(np.asarray(o1)[:, :7], np.asarray(o2)[:, :7], atol=1e-4)


def test_encoder_post_ln_variant(rng):
    enc = TransformerEncoder(
        encoder_layers=1, embed_dim=16, ffn_embed_dim=32, attention_heads=2,
        max_seq_len=8, post_ln=True,
        emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
    )
    emb = jnp.asarray(rng.randn(1, 8, 16).astype(np.float32))
    params = enc.init(jax.random.PRNGKey(0), emb)
    # post-LN has no final_layer_norm (reference transformer_encoder.py:75-78)
    assert "final_layer_norm" not in params["params"]
    assert enc.apply(params, emb).shape == (1, 8, 16)


def test_encoder_dropout_rng_determinism(rng):
    enc = TransformerEncoder(
        encoder_layers=1, embed_dim=16, ffn_embed_dim=32, attention_heads=2,
        max_seq_len=8, emb_dropout=0.5, dropout=0.5, attention_dropout=0.5,
    )
    emb = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
    params = enc.init(jax.random.PRNGKey(0), emb)
    d1 = enc.apply(params, emb, deterministic=False,
                   rngs={"dropout": jax.random.PRNGKey(1)})
    d2 = enc.apply(params, emb, deterministic=False,
                   rngs={"dropout": jax.random.PRNGKey(1)})
    d3 = enc.apply(params, emb, deterministic=False,
                   rngs={"dropout": jax.random.PRNGKey(2)})
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    assert np.abs(np.asarray(d1) - np.asarray(d3)).max() > 1e-3


def test_decoder_causality(rng):
    """Changing a future token must not change past outputs."""
    B, T, E = 1, 8, 16
    dec = TransformerDecoder(
        decoder_layers=2, embed_dim=E, ffn_embed_dim=32, attention_heads=2,
        max_seq_len=8, auto_regressive=True,
        emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
    )
    x = rng.randn(B, T, E).astype(np.float32)
    params = dec.init(jax.random.PRNGKey(0), jnp.asarray(x))
    o1 = dec.apply(params, jnp.asarray(x))
    x2 = x.copy()
    # random perturbation (a constant shift would be cancelled by the
    # embedding LayerNorm)
    x2[:, 5:] += rng.randn(B, T - 5, E).astype(np.float32)
    o2 = dec.apply(params, jnp.asarray(x2))
    np.testing.assert_allclose(np.asarray(o1)[:, :5], np.asarray(o2)[:, :5], atol=1e-4)
    assert np.abs(np.asarray(o1)[:, 5:] - np.asarray(o2)[:, 5:]).max() > 1e-3


def test_decoder_cross_attention(rng):
    B, T, S, E = 2, 6, 9, 16
    dec = TransformerDecoder(
        decoder_layers=1, embed_dim=E, ffn_embed_dim=32, attention_heads=2,
        max_seq_len=8, emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
    )
    x = jnp.asarray(rng.randn(B, T, E).astype(np.float32))
    enc_out = jnp.asarray(rng.randn(B, S, E).astype(np.float32))
    params = dec.init(jax.random.PRNGKey(0), x, encoder_out=enc_out)
    out = dec.apply(params, x, encoder_out=enc_out)
    assert out.shape == (B, T, E)
    # encoder output actually matters
    out2 = dec.apply(params, x, encoder_out=enc_out + 1.0)
    assert np.abs(np.asarray(out) - np.asarray(out2)).max() > 1e-4


def test_encoder_checkpoint_activations(rng):
    """Activation-checkpointed encoder must match the plain encoder exactly
    (regression: remat static_argnums indexing)."""
    B, T, E = 2, 8, 32
    kw = dict(
        encoder_layers=2, embed_dim=E, ffn_embed_dim=64, attention_heads=4,
        max_seq_len=8, emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
    )
    enc = TransformerEncoder(**kw)
    enc_ckpt = TransformerEncoder(checkpoint_activations=True, **kw)
    emb = jnp.asarray(rng.randn(B, T, E).astype(np.float32))
    params = enc.init(jax.random.PRNGKey(0), emb)
    o1 = enc.apply(params, emb)
    o2 = enc_ckpt.apply(params, emb)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)

    g1 = jax.grad(lambda p: jnp.sum(enc.apply(p, emb) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(enc_ckpt.apply(p, emb) ** 2))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
