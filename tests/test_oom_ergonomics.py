"""OOM ergonomics (closes SURVEY §5.3 partial / VERDICT r3 next-7): the
compiled step's memory analysis is checked against HBM BEFORE the first
step, and allocator failures carry a per-buffer breakdown + concrete
mitigation knobs — the TPU analogue of the reference's OOM
catch-log-retry (``unicore/trainer.py:639-654``)."""

import logging

import numpy as np
import pytest

from tests.test_trainer import make_batch, make_trainer  # noqa: F401
from unicore_tpu import metrics


def _capture(logger_name):
    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(
        (rec.levelno, rec.getMessage())
    )
    lg = logging.getLogger(logger_name)
    lg.addHandler(handler)
    lg.setLevel(logging.DEBUG)
    return records, handler, lg


def test_preflight_memory_analysis_logged(rng):
    """The first dispatch AOT-compiles and logs the memory breakdown."""
    records, handler, lg = _capture("unicore_tpu.trainer")
    try:
        metrics.reset()
        trainer = make_trainer()
        with metrics.aggregate("train"):
            trainer.train_step([make_batch(rng)])
    finally:
        lg.removeHandler(handler)
    msgs = [m for _, m in records if "train step memory" in m]
    assert msgs, records
    assert "temporaries_gb" in msgs[0]
    assert trainer._memory_analysis is not None
    assert trainer._memory_analysis["estimated_peak_gb"] >= 0


def test_preflight_warns_when_estimate_exceeds_hbm(rng, monkeypatch):
    """A config whose compiled footprint exceeds the device limit warns
    with the breakdown and the mitigation knobs BEFORE the step runs."""
    metrics.reset()
    trainer = make_trainer()
    monkeypatch.setattr(
        trainer, "_device_memory_stats", lambda: {"bytes_limit": 1024}
    )
    records, handler, lg = _capture("unicore_tpu.trainer")
    try:
        with metrics.aggregate("train"):
            trainer.train_step([make_batch(rng)])
    finally:
        lg.removeHandler(handler)
    errs = [m for lvl, m in records if lvl >= logging.ERROR]
    assert errs, records
    assert "will likely OOM" in errs[0]
    assert "--checkpoint-activations" in errs[0]
    assert "--update-freq" in errs[0]


def test_allocator_failure_carries_guidance(rng, monkeypatch):
    """A RESOURCE_EXHAUSTED dispatch failure logs the mitigation knobs
    (and the breakdown captured at compile time) before re-raising."""
    metrics.reset()
    trainer = make_trainer()
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])  # compile + one good step

    def boom(*a, **k):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 123 bytes"
        )

    monkeypatch.setattr(trainer, "_compiled_train_step", boom)
    records, handler, lg = _capture("unicore_tpu.trainer")
    try:
        with metrics.aggregate("train"):
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                trainer.train_step([batch])
    finally:
        lg.removeHandler(handler)
    errs = " ".join(m for lvl, m in records if lvl >= logging.ERROR)
    assert "mitigation knobs" in errs
    assert "--fsdp-size" in errs and "--batch-size" in errs
    assert "Compile-time breakdown" in errs


def test_non_oom_failure_skips_guidance(rng, monkeypatch):
    """Unrelated dispatch failures must NOT spam the OOM advice."""
    metrics.reset()
    trainer = make_trainer()
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])

    def boom(*a, **k):
        raise RuntimeError("something unrelated went wrong")

    monkeypatch.setattr(trainer, "_compiled_train_step", boom)
    records, handler, lg = _capture("unicore_tpu.trainer")
    try:
        with metrics.aggregate("train"):
            with pytest.raises(RuntimeError, match="unrelated"):
                trainer.train_step([batch])
    finally:
        lg.removeHandler(handler)
    assert not any(
        "mitigation knobs" in m for _, m in records
    ), records
