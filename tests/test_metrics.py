"""Metrics/meters tests (reference behavior: unicore/logging/)."""

import pytest

from unicore_tpu.logging import meters, metrics


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def test_average_meter_weighted():
    m = meters.AverageMeter()
    m.update(1.0, 1)
    m.update(3.0, 3)
    assert m.avg == pytest.approx(2.5)
    assert m.val == 3.0


def test_nested_aggregation():
    with metrics.aggregate("train") as outer:
        metrics.log_scalar("loss", 1.0)
        with metrics.aggregate() as inner:
            metrics.log_scalar("loss", 3.0)
    # outer saw both, inner only the second
    assert outer.get_smoothed_value("loss") == pytest.approx(2.0)
    assert inner.get_smoothed_value("loss") == pytest.approx(3.0)


def test_new_root_isolation():
    with metrics.aggregate("train"):
        metrics.log_scalar("loss", 1.0)
        with metrics.aggregate("valid", new_root=True):
            metrics.log_scalar("loss", 9.0)
        metrics.log_scalar("loss", 3.0)
    assert metrics.get_smoothed_value("train", "loss") == pytest.approx(2.0)
    assert metrics.get_smoothed_value("valid", "loss") == pytest.approx(9.0)


def test_derived_meter():
    with metrics.aggregate("train"):
        metrics.log_scalar("a", 4.0)
        metrics.log_derived("b", lambda m: m["a"].avg * 2)
    assert metrics.get_smoothed_value("train", "b") == pytest.approx(8.0)


def test_state_dict_roundtrip():
    with metrics.aggregate("train"):
        metrics.log_scalar("loss", 2.5, weight=4)
    state = metrics.state_dict()
    metrics.reset()
    metrics.load_state_dict(state)
    assert metrics.get_smoothed_value("train", "loss") == pytest.approx(2.5)


def test_meters_dict_priority_order():
    md = meters.MetersDict()
    md.add_meter("z", meters.AverageMeter(), priority=10)
    md.add_meter("a", meters.AverageMeter(), priority=50)
    md.add_meter("m", meters.AverageMeter(), priority=20)
    assert list(md.keys()) == ["z", "m", "a"]


def test_jax_scalar_coercion():
    import jax.numpy as jnp

    with metrics.aggregate("train"):
        metrics.log_scalar("loss", jnp.float32(2.0), weight=jnp.int32(2))
    assert metrics.get_smoothed_value("train", "loss") == pytest.approx(2.0)


def test_log_scalar_does_not_clobber_derived_meter():
    """A scalar logged under a derived key is ignored (the trainer
    re-logs reduced stats dicts that can include derived entries, e.g. a
    loss's ppl)."""
    from unicore_tpu import metrics

    metrics.reset()
    with metrics.aggregate("t") as agg:
        metrics.log_scalar("loss", 2.0, 1)
        metrics.log_derived("ppl", lambda m: 2 ** m["loss"].avg)
        metrics.log_scalar("ppl", 123.0)  # must not crash or clobber
        vals = agg.get_smoothed_values()
    assert vals["ppl"] == 4.0
