"""Fused chunked linear+cross-entropy head (ops/fused_cross_entropy,
ISSUE 10): gradient parity against the naive fp32 reference for every
head form the losses wire it into — full-sequence weighted-mask MLM,
static-slot [K, V] (including >K overflow), plain and token-weighted
cross-entropy — plus bf16 inputs, tied and untied kernels, chunk sizes
that do not divide N, and the memory contract (no [N, V]-sized buffer in
the fused jaxpr, forward or backward)."""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu.ops import fused_cross_entropy as fce
from unicore_tpu.ops.fused_cross_entropy import (
    fused_linear_cross_entropy,
    linear_nll_reference,
)

# ---------------------------------------------------------------------------
# op-level parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tied", [True, False])
@pytest.mark.parametrize("has_bias", [True, False])
@pytest.mark.parametrize("chunk", [17, 32, 100, 256])
def test_fused_matches_reference_fp32(rng, tied, has_bias, chunk):
    """fp32: chunked == materialized to float tolerance, for loss AND
    d(features)/d(kernel)/d(bias), including non-dividing chunks (17 on
    N=100) and a chunk above N (256 -> one clamped chunk)."""
    n, d, v = 100, 24, 41
    f = jnp.asarray(rng.randn(n, d), jnp.float32)
    k = jnp.asarray(rng.randn(*((v, d) if tied else (d, v))), jnp.float32)
    b = jnp.asarray(rng.randn(v), jnp.float32) if has_bias else None
    t = jnp.asarray(rng.randint(0, v, n), jnp.int32)
    w = jnp.asarray((rng.rand(n) < 0.6).astype(np.float32))

    def ref(f_, k_, b_):
        return jnp.sum(
            linear_nll_reference(f_, k_, t, bias=b_, tied=tied) * w
        )

    def fus(f_, k_, b_):
        return jnp.sum(fused_linear_cross_entropy(
            f_, k_, t, bias=b_, tied=tied, chunk_size=chunk) * w)

    l_ref, l_fus = ref(f, k, b), jax.jit(fus)(f, k, b)
    np.testing.assert_allclose(l_fus, l_ref, rtol=1e-5)
    g_ref = jax.grad(ref, argnums=(0, 1) + ((2,) if has_bias else ()))(
        f, k, b)
    g_fus = jax.jit(jax.grad(
        fus, argnums=(0, 1) + ((2,) if has_bias else ())))(f, k, b)
    for a, c in zip(g_ref, g_fus):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a), atol=2e-5)


def test_fused_bf16_tracks_fp32_oracle(rng):
    """bf16 inputs: the fused path (fp32 MXU accumulation per chunk)
    must stay at least as close to the fp32 oracle as the naive bf16
    path is, and the two bf16 paths must agree within bf16 noise."""
    n, d, v = 96, 32, 128
    f32 = rng.randn(n, d).astype(np.float32)
    k32 = rng.randn(v, d).astype(np.float32)
    t = jnp.asarray(rng.randint(0, v, n), jnp.int32)

    oracle = linear_nll_reference(
        jnp.asarray(f32), jnp.asarray(k32), t, tied=True)
    f16, k16 = jnp.asarray(f32, jnp.bfloat16), jnp.asarray(k32, jnp.bfloat16)
    naive = linear_nll_reference(f16, k16, t, tied=True)
    fused = jax.jit(lambda a, b: fused_linear_cross_entropy(
        a, b, t, tied=True, chunk_size=32))(f16, k16)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(naive),
                               atol=0.15)
    err = lambda x: float(jnp.max(jnp.abs(x - oracle)))
    assert err(fused) <= err(naive) + 1e-3, (err(fused), err(naive))

    # bf16 gradient parity against the fp32 oracle, loose bf16 tolerance
    loss_o = lambda a, b: jnp.sum(linear_nll_reference(a, b, t, tied=True))
    loss_f = lambda a, b: jnp.sum(fused_linear_cross_entropy(
        a, b, t, tied=True, chunk_size=32))
    go = jax.grad(loss_o, argnums=(0, 1))(jnp.asarray(f32), jnp.asarray(k32))
    gf = jax.jit(jax.grad(loss_f, argnums=(0, 1)))(f16, k16)
    for a, c in zip(go, gf):
        np.testing.assert_allclose(
            np.asarray(c, np.float32), np.asarray(a), atol=0.08)


def test_fused_jaxpr_never_materializes_logits(rng):
    """The tentpole contract, checked by the same rule CI gates on: no
    intermediate as large as the [N, V] logits exists in the jitted
    fwd+bwd program — while the reference path trips the identical
    budget."""
    from unicore_tpu.analysis.trace_audit import audit_jaxpr

    n, d, v, chunk = 512, 16, 256, 64
    f = jnp.asarray(rng.randn(n, d), jnp.float32)
    k = jnp.asarray(rng.randn(v, d), jnp.float32)
    t = jnp.asarray(rng.randint(0, v, n), jnp.int32)
    budget = n * v * 4

    def make(impl):
        def loss(f_, k_):
            return jnp.sum(impl(f_, k_))

        return jax.jit(jax.grad(loss, argnums=(0, 1)))

    fused = make(lambda f_, k_: fused_linear_cross_entropy(
        f_, k_, t, tied=True, chunk_size=chunk))
    naive = make(lambda f_, k_: linear_nll_reference(f_, k_, t, tied=True))
    got_fused = audit_jaxpr(jax.make_jaxpr(fused)(f, k), big_bytes=budget)
    got_naive = audit_jaxpr(jax.make_jaxpr(naive)(f, k), big_bytes=budget)
    assert got_fused == [], "\n".join(x.message for x in got_fused)
    assert any(x.rule == "UL002" for x in got_naive)


def test_dispatch_heuristics_and_overrides(rng, monkeypatch):
    """Auto dispatch: small vocab*rows -> the unfused reference (eager
    crossover); past the byte floor -> chunked with the heuristic
    chunk; an explicit chunk_size always takes the chunked path."""
    called = {}
    real = fce._chunked_nll

    def spy(chunk, tied, *args):
        called["chunk"] = chunk
        return real(chunk, tied, *args)

    monkeypatch.setattr(fce, "_chunked_nll", spy)
    f = jnp.zeros((64, 8), jnp.float32)
    k = jnp.zeros((32, 8), jnp.float32)
    t = jnp.zeros((64,), jnp.int32)
    fused_linear_cross_entropy(f, k, t, tied=True)  # 64*32*4 « FUSE_MIN
    assert "chunk" not in called
    # a non-positive explicit chunk means auto, never a 1-row scan
    fused_linear_cross_entropy(f, k, t, tied=True, chunk_size=-1)
    assert "chunk" not in called
    fused_linear_cross_entropy(f, k, t, tied=True, chunk_size=16)
    assert called.pop("chunk") == 16
    # past the byte floor but pick_chunk cannot split the rows: a
    # single-chunk "fused" program saves nothing — stays eager
    monkeypatch.setattr(fce, "FUSE_MIN_BYTES", 1)
    assert fce.pick_chunk(64, 32) >= 64
    fused_linear_cross_entropy(f, k, t, tied=True)
    assert "chunk" not in called
    # genuinely chunkable shape takes the heuristic chunk
    f2 = jnp.zeros((256, 8), jnp.float32)
    k2 = jnp.zeros((65536, 8), jnp.float32)
    t2 = jnp.zeros((256,), jnp.int32)
    assert fce.pick_chunk(256, 65536) == 128
    fused_linear_cross_entropy(f2, k2, t2, tied=True)
    assert called.pop("chunk") == 128


def test_pick_chunk_bounds():
    assert fce.pick_chunk(8192, 30528) == 256  # 32 MiB fp32 budget
    assert fce.pick_chunk(8192, 128) <= 8192
    assert fce.pick_chunk(100, 30528) == 100  # clamped to the row count
    assert fce.pick_chunk(8192, 10_000_000) == fce.MIN_CHUNK


# ---------------------------------------------------------------------------
# loss-level parity (the three wired forms)
# ---------------------------------------------------------------------------

VOCAB, PAD = 32, 0


def _bert(capacity):
    from examples.bert.model import BertModel

    return BertModel(
        vocab_size=VOCAB, padding_idx=PAD, encoder_layers=1,
        encoder_embed_dim=32, encoder_ffn_embed_dim=64,
        encoder_attention_heads=2, emb_dropout=0.0, dropout=0.0,
        attention_dropout=0.0, activation_dropout=0.0, max_seq_len=64,
        masked_loss_capacity=capacity,
    )


def _mlm_loss(fused, chunk=0):
    from unicore_tpu.losses.masked_lm import MaskedLMLoss

    task = SimpleNamespace(
        dictionary=SimpleNamespace(pad=lambda: PAD),
        args=SimpleNamespace(fused_lm_head="on" if fused else "off",
                             fused_ce_chunk=chunk),
    )
    return MaskedLMLoss(task)


def _mlm_sample(rng, bsz, seq, n_masked):
    toks = rng.randint(4, VOCAB, size=(bsz, seq)).astype(np.int64)
    target = np.full((bsz, seq), PAD, dtype=np.int64)
    flat = target.reshape(-1)
    pick = rng.choice(bsz * seq, size=n_masked, replace=False)
    flat[pick] = rng.randint(4, VOCAB, size=n_masked)
    return {"net_input": {"src_tokens": toks}, "target": target}


@pytest.mark.parametrize("capacity,bsz,seq,n_masked", [
    (0.25, 4, 16, 12),    # static-slot head, everything fits
    (0.0, 4, 16, 12),     # full-sequence weighted-mask head
    # slot OVERFLOW: K = ceil128(0.05*256) = 128 slots < 140 masked —
    # the excess drops from numerator AND denominator on both paths
    (0.05, 4, 64, 140),
])
def test_masked_lm_fused_matches_naive(rng, capacity, bsz, seq, n_masked):
    model = _bert(capacity)
    sample = _mlm_sample(rng, bsz, seq, n_masked)
    params = model.init(
        jax.random.PRNGKey(0), sample["net_input"]["src_tokens"],
        masked_tokens=(sample["target"] != PAD),
    )["params"]

    def run(fused):
        loss_fn = _mlm_loss(fused, chunk=7)  # non-dividing on purpose

        def scalar(p):
            loss, size, _ = loss_fn.forward(
                model, p, sample, is_training=False)
            return loss, size

        (loss, size), grads = jax.value_and_grad(scalar, has_aux=True)(
            params)
        return loss, size, grads

    (l_f, s_f, g_f), (l_n, s_n, g_n) = run(True), run(False)
    np.testing.assert_allclose(l_f, l_n, rtol=1e-5)
    np.testing.assert_allclose(s_f, s_n)
    flat_f = jax.tree_util.tree_leaves_with_path(g_f)
    flat_n = dict(jax.tree_util.tree_leaves_with_path(g_n))
    for path, leaf in flat_f:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_n[path]), atol=3e-5,
            err_msg=jax.tree_util.keystr(path),
        )


def _lm_model():
    from examples.lm.model import TransformerLMModel

    return TransformerLMModel(
        vocab_size=VOCAB, padding_idx=PAD, decoder_layers=1,
        decoder_embed_dim=32, decoder_ffn_embed_dim=64,
        decoder_attention_heads=2, emb_dropout=0.0, dropout=0.0,
        attention_dropout=0.0, activation_dropout=0.0, max_seq_len=64,
        rel_pos=False, abs_pos=True,
    )


@pytest.mark.parametrize("loss_name", ["cross_entropy", "lm_cross_entropy"])
def test_lm_losses_fused_match_naive(rng, loss_name):
    """Plain cross-entropy (every position) and the LM plugin's
    token-weighted variant, through the decoder LM's tied head."""
    import examples.lm.loss  # noqa: F401 - registers lm_cross_entropy
    from unicore_tpu.losses import LOSS_REGISTRY

    model = _lm_model()
    toks = rng.randint(4, VOCAB, size=(2, 12)).astype(np.int64)
    tgt = np.roll(toks, -1, axis=1)
    tgt[:, -1] = PAD
    sample = {"net_input": {"src_tokens": toks}, "target": tgt}
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    def run(fused):
        task = SimpleNamespace(
            dictionary=SimpleNamespace(pad=lambda: PAD),
            args=SimpleNamespace(fused_lm_head="on" if fused else "off",
                                 fused_ce_chunk=5),
        )
        loss_fn = LOSS_REGISTRY[loss_name](task)

        def scalar(p):
            return loss_fn.forward(model, p, sample, is_training=False)[0]

        return jax.value_and_grad(scalar)(params)

    (l_f, g_f), (l_n, g_n) = run(True), run(False)
    np.testing.assert_allclose(l_f, l_n, rtol=1e-5)
    for a, c in zip(jax.tree_util.tree_leaves(g_f),
                    jax.tree_util.tree_leaves(g_n)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=3e-5)


def test_fused_and_naive_share_param_structure(rng):
    """A checkpoint trained with the fused head must restore into the
    materialized head and vice versa: init under either mode yields the
    identical parameter tree."""
    model = _bert(0.25)
    toks = rng.randint(4, VOCAB, size=(2, 8)).astype(np.int64)
    mask = np.zeros((2, 8), bool)
    mask[:, 1] = True
    p_naive = model.init(jax.random.PRNGKey(0), toks, masked_tokens=mask)
    p_fused = model.init(jax.random.PRNGKey(0), toks, masked_tokens=mask,
                         fused_head=True)
    assert jax.tree_util.tree_structure(p_naive) \
        == jax.tree_util.tree_structure(p_fused)
    for a, c in zip(jax.tree_util.tree_leaves(p_naive),
                    jax.tree_util.tree_leaves(p_fused)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
