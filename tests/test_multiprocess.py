"""True multi-process coverage (VERDICT r2 item 7): two OS processes run
``jax.distributed.initialize`` over a local TCP coordinator on the CPU
backend (2 virtual devices each -> a 4-device global mesh) and exercise
the ``process_count() > 1`` branches that single-process tests never
reach:

- ``distributed.all_gather_objects`` (pickle allgather, ordered);
- the ragged-tail micro-batch weight reconcile in
  ``Trainer._stack_microbatches`` (slot weights min-reduced across hosts);
- ``jax.make_array_from_process_local_data`` global-batch assembly in
  ``Trainer._to_device``.

Run as a worker: ``python tests/test_multiprocess.py <pid> <port>``.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(pid, port):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("UNICORE_TPU_TEST_ON_TPU", None)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), str(pid), str(port)],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def test_two_process_trainer_and_collectives():
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = [_spawn(i, port) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process workers timed out:\n" +
                    "\n".join(o or "" for o in outs))
    if any("WORKER_SKIP_NO_MP_ALLGATHER" in (o or "") for o in outs):
        # capability probe: some jaxlib CPU backends cannot run
        # multi-process computations at all ("Multiprocess computations
        # aren't implemented on the CPU backend") — nothing this test
        # covers is reachable there, so skip instead of failing every
        # run in such containers
        pytest.skip("CPU backend lacks multiprocess allgather")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert "WORKER_OK" in out, f"worker {i} incomplete:\n{out[-4000:]}"


# ---------------------------------------------------------------------------
# worker body
# ---------------------------------------------------------------------------


def _worker(pid, port):
    import jax

    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4

    # capability probe BEFORE the real assertions: a trivial allgather
    # either works (backend supports multi-process computations) or
    # raises the backend's not-implemented error, in which case the
    # host test skips cleanly instead of failing
    import numpy as np
    from jax.experimental import multihost_utils

    try:
        multihost_utils.process_allgather(np.zeros((1,), dtype=np.int32))
    except Exception as e:
        if ("aren't implemented" in str(e)
                or "not implemented" in str(e).lower()):
            print("WORKER_SKIP_NO_MP_ALLGATHER", pid)
            return
        raise

    import logging
    from argparse import Namespace

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, REPO)
    from unicore_tpu import metrics
    from unicore_tpu.distributed import utils as dist_utils
    from unicore_tpu.losses.unicore_loss import UnicoreLoss
    from unicore_tpu.models.unicore_model import BaseUnicoreModel
    from unicore_tpu.tasks.unicore_task import UnicoreTask
    from unicore_tpu.trainer import Trainer

    # -- all_gather_objects: ordered, arbitrary payloads ---------------
    got = dist_utils.all_gather_objects({"rank": pid, "tag": "x" * (pid + 1)})
    assert [g["rank"] for g in got] == [0, 1], got
    assert got[1]["tag"] == "xx"

    # -- trainer over the 2-process mesh --------------------------------
    VOCAB, DIM = 13, 16

    class ToyModel(BaseUnicoreModel):
        @nn.compact
        def __call__(self, src_tokens, deterministic=True, **kw):
            x = nn.Embed(VOCAB, DIM, name="embed")(src_tokens)
            return nn.Dense(VOCAB, name="out")(x)

    class ToyLoss(UnicoreLoss):
        def forward(self, model, params, sample, rng=None, is_training=True):
            logits = model.apply(
                {"params": params}, **sample["net_input"],
                deterministic=not is_training,
            )
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            t = sample["target"]
            nll = -jnp.take_along_axis(lp, t[..., None], axis=-1)[..., 0]
            n = jnp.asarray(np.prod(t.shape), dtype=jnp.float32)
            return jnp.sum(nll), n, {"loss": jnp.sum(nll), "sample_size": n,
                                     "bsz": jnp.float32(t.shape[0])}

        @staticmethod
        def reduce_metrics(logging_outputs, split="train"):
            n = sum(float(l.get("sample_size", 0)) for l in logging_outputs)
            loss = sum(float(l.get("loss", 0)) for l in logging_outputs)
            metrics.log_scalar("loss", loss / max(n, 1), n, round=3)

        @staticmethod
        def logging_outputs_can_be_summed(is_train):
            return True

    class ToyTask(UnicoreTask):
        pass

    args = Namespace(
        seed=1, update_freq=[2], clip_norm=0.0, ema_decay=-1.0,
        fp16=False, bf16=False, bf16_sr=False, stats_lag=0,
        optimizer="adam", lr=[1e-2], adam_betas="(0.9, 0.999)",
        adam_eps=1e-8, weight_decay=0.0,
        lr_scheduler="fixed", force_anneal=None, lr_shrink=0.1,
        warmup_updates=0, min_loss_scale=1e-4, fp16_scale_window=None,
        fp16_init_scale=4.0, max_update=10, max_epoch=0,
        tensor_parallel_size=1, seq_parallel_size=1, fsdp_size=1,
    )
    task = ToyTask(args)
    trainer = Trainer(args, task, ToyModel(), ToyLoss(task))

    def local_batch(seed):
        rng = np.random.RandomState(seed)
        # per-host LOCAL shard: 4 rows here, 8 global
        toks = rng.randint(0, VOCAB, size=(4, 8)).astype(np.int64)
        return {"net_input": {"src_tokens": toks}, "target": toks.copy()}

    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    trainer_logger = logging.getLogger("unicore_tpu.trainer")
    trainer_logger.addHandler(handler)
    trainer_logger.setLevel(logging.INFO)

    metrics.reset()
    with metrics.aggregate("train"):
        # step 1: both hosts real in both slots
        logs = trainer.train_step([local_batch(0), local_batch(1)])
        assert float(logs[0]["sample_size"]) == 2 * 8 * 8  # 2 slots x global
        # step 2, ragged tail: host 1's second slot is empty -> the slot is
        # min-reconciled to weight 0 on BOTH hosts
        second = [local_batch(2), local_batch(3) if pid == 0 else None]
        logs = trainer.train_step(second)
        assert float(logs[0]["sample_size"]) == 8 * 8, logs

    assert trainer.get_num_updates() == 2
    if pid == 0:
        assert any("ragged-tail" in m for m in records), records

    # params stay replicated and identical across hosts
    leaf = np.asarray(
        jax.device_get(jax.tree_util.tree_leaves(trainer.state["params"])[0])
    )
    digests = dist_utils.all_gather_objects(float(np.sum(leaf)))
    assert np.allclose(digests[0], digests[1]), digests

    # -- checkpoint round trip under 2 processes ------------------------
    # process 0 writes; EVERY host reads the same file (SPMD: no
    # rank-0-read + broadcast_object like the reference trainer.py:356-382)
    import tempfile

    ckpt_dir = dist_utils.all_gather_objects(
        tempfile.mkdtemp(prefix="mp_ckpt_") if pid == 0 else None
    )[0]
    path = os.path.join(ckpt_dir, "checkpoint_mp.pt")
    trainer.save_checkpoint(path, {"epoch": 1})
    # barrier so host 1 never reads a half-written file
    dist_utils.all_gather_objects(("saved", pid))

    trainer2 = Trainer(args, task, ToyModel(), ToyLoss(task))
    extra = trainer2.load_checkpoint(path)
    assert extra is not None and extra.get("epoch") == 1
    assert trainer2.get_num_updates() == 2
    trainer2.init_state(local_batch(0))  # deferred restore materializes
    l1 = jax.tree_util.tree_leaves(trainer.state["params"])[0]
    l2 = jax.tree_util.tree_leaves(trainer2.state["params"])[0]
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(l1)), np.asarray(jax.device_get(l2))
    )
    # the restored trainer can keep stepping in lockstep
    metrics.reset()
    with metrics.aggregate("train"):
        logs = trainer2.train_step([local_batch(5)])
    assert float(logs[0]["sample_size"]) == 8 * 8
    assert trainer2.get_num_updates() == 3

    # -- SHARDED checkpoint under fsdp spanning both processes ----------
    # fsdp_size=4 puts every device on the ZeRO axis: each process holds
    # 2 of the 4 pieces of each sharded leaf and must save/restore ONLY
    # those — no host ever materializes the full state (VERDICT r3
    # next-3 "done" condition).
    import pickle

    args_f = Namespace(**{**vars(args), "fsdp_size": 4})
    dist_utils.reset_mesh()
    task_f = ToyTask(args_f)
    trainer_f = Trainer(args_f, task_f, ToyModel(), ToyLoss(task_f))
    metrics.reset()
    with metrics.aggregate("train"):
        trainer_f.train_step([local_batch(6), local_batch(7)])

    def digest(t):
        tot = jax.jit(
            lambda p: sum(
                jnp.sum(x.astype(jnp.float64))
                for x in jax.tree_util.tree_leaves(p)
            )
        )(t.state["params"])
        return float(tot)

    d_before = digest(trainer_f)
    path_f = os.path.join(ckpt_dir, "checkpoint_fsdp.pt")
    trainer_f.save_checkpoint(path_f, {"epoch": 1})
    dist_utils.all_gather_objects(("saved_fsdp", pid))

    # each process's shard file holds a strict subset of the sharded bytes
    with open(path_f + f".shard{pid}", "rb") as f:
        payload = pickle.load(f)
    own = sum(
        np.asarray(piece).size
        for entries in payload["entries"].values()
        for _, piece in entries
    )
    total_sharded = sum(
        leaf.size
        for leaf in jax.tree_util.tree_leaves(trainer_f.state)
        if hasattr(leaf, "sharding") and not leaf.sharding.is_fully_replicated
    )
    assert 0 < own < total_sharded, (own, total_sharded)

    records.clear()
    trainer_f2 = Trainer(args_f, task_f, ToyModel(), ToyLoss(task_f))
    trainer_f2.load_checkpoint(path_f)
    trainer_f2.init_state(local_batch(6))
    # same topology: the per-process fast path, never the full-assembly
    # fallback
    assert not any("shard layout changed" in m for m in records), records
    assert abs(digest(trainer_f2) - d_before) < 1e-9
    metrics.reset()
    with metrics.aggregate("train"):
        trainer_f.train_step([local_batch(8)])
        trainer_f2.train_step([local_batch(8)])
    assert abs(digest(trainer_f2) - digest(trainer_f)) < 1e-9

    # mid-run reload of a SHARDED checkpoint: state is already built, so
    # the restore must rebuild through the deferred init path (a plain
    # device_get would touch non-addressable shards and raise)
    trainer_f2.load_checkpoint(path_f)
    assert abs(digest(trainer_f2) - d_before) < 1e-9

    # -- fsdp=2 x data=2: one process owns ZERO shard pieces ------------
    # every fsdp piece is replicated across the cross-process data axis,
    # so the lowest-process-index owner rule hands all of them to process
    # 0.  The save must still complete: the shard-token collective runs
    # on EVERY process at the same program point, not just on owners
    # (otherwise the owners block forever in the allgather).
    args_h = Namespace(**{**vars(args), "fsdp_size": 2})
    dist_utils.reset_mesh()
    task_h = ToyTask(args_h)
    trainer_h = Trainer(args_h, task_h, ToyModel(), ToyLoss(task_h))
    metrics.reset()
    with metrics.aggregate("train"):
        trainer_h.train_step([local_batch(9)])
    d_h = digest(trainer_h)
    path_h = os.path.join(ckpt_dir, "checkpoint_fsdp2.pt")
    trainer_h.save_checkpoint(path_h, {"epoch": 1})
    dist_utils.all_gather_objects(("saved_fsdp2", pid))
    assert not os.path.exists(path_h + ".shard1"), \
        "process 1 owns no pieces and must not write a shard file"
    trainer_h2 = Trainer(args_h, task_h, ToyModel(), ToyLoss(task_h))
    trainer_h2.load_checkpoint(path_h)
    trainer_h2.init_state(local_batch(9))
    assert abs(digest(trainer_h2) - d_h) < 1e-9

    # -- tensor parallelism with dp spanning the two processes ----------
    # mesh reshape puts tp innermost: tp=2 pairs each process's two local
    # devices while the data axis crosses processes — the realistic
    # multi-host layout (tp over ICI within a host, dp across hosts)
    args_t = Namespace(**{**vars(args), "tensor_parallel_size": 2})
    dist_utils.reset_mesh()
    task_t = ToyTask(args_t)

    class AttnModel(BaseUnicoreModel):
        @nn.compact
        def __call__(self, src_tokens, deterministic=True, **kw):
            from unicore_tpu.modules import SelfMultiheadAttention

            x = nn.Embed(VOCAB, DIM, name="embed")(src_tokens)
            x = x + SelfMultiheadAttention(
                embed_dim=DIM, num_heads=4, dropout=0.0, name="attn"
            )(x, deterministic=deterministic)
            return nn.Dense(VOCAB, name="out")(x)

    trainer_t = Trainer(args_t, task_t, AttnModel(), ToyLoss(task_t))
    metrics.reset()
    with metrics.aggregate("train"):
        logs = trainer_t.train_step([local_batch(10), local_batch(11)])
    assert float(logs[0]["sample_size"]) == 2 * 8 * 8
    k = trainer_t.state["params"]["attn"]["in_proj"]["kernel"]
    assert not k.sharding.is_fully_replicated, "tp did not shard weights"
    digests = dist_utils.all_gather_objects(digest(trainer_t))
    assert np.allclose(digests[0], digests[1]), digests

    print("WORKER_OK", pid)


if __name__ == "__main__":
    _worker(int(sys.argv[1]), int(sys.argv[2]))
