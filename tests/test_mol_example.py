"""Uni-Mol example plugin e2e: synthetic conformers through the full CLI —
the gaussian-pair-bias attention path plus 2-D pair collation
(BASELINE configs[1], the one reference workload no other example
covers)."""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("moldata"))
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "examples", "mol", "example_data", "make_data.py"),
         "-o", data_dir, "--train", "64", "--valid", "8",
         "--min-atoms", "6", "--max-atoms", "12"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return data_dir


def test_mol_cli_trains_and_loss_decreases(corpus, tmp_path):
    save_dir = str(tmp_path / "ckpt")
    cmd = [
        sys.executable, "-m", "unicore_tpu_cli.train", corpus,
        "--user-dir", os.path.join(REPO, "examples", "mol"),
        "--task", "mol", "--loss", "unimol", "--arch", "unimol",
        "--encoder-layers", "2", "--encoder-embed-dim", "32",
        "--encoder-ffn-embed-dim", "64", "--encoder-attention-heads", "2",
        "--pair-hidden-dim", "8", "--gaussian-kernels", "8",
        "--max-atoms", "12", "--mask-prob", "0.3",
        "--batch-size", "8", "--optimizer", "adam", "--lr", "1e-3",
        "--lr-scheduler", "fixed", "--max-update", "16",
        "--log-interval", "4", "--log-format", "simple",
        "--save-dir", save_dir,
        "--required-batch-size-multiple", "1", "--num-workers", "0", "--cpu",
    ]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=560, env=env, cwd=REPO
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "done training" in r.stdout
    # all three objective terms surface in the stats line
    for key in ("token_loss", "coord_loss", "dist_loss", "coord_rmsd"):
        assert key in r.stdout, key
    assert os.path.exists(os.path.join(save_dir, "checkpoint_last.pt"))

    losses = [float(m) for m in re.findall(r"\| loss ([\d.]+) \|", r.stdout)]
    assert len(losses) >= 2 and losses[-1] < losses[0], losses
