"""Bucketed per-layer collective scheduling (ISSUE 17 tentpole A,
``--comms-overlap`` / ``--comms-bucket-mb``).

Tiers:

- ``comm_bucket_assignment`` units: determinism (pure function of tree
  structure + shapes + dtypes + cap), every-leaf-in-exactly-one-bucket
  coverage, cap respected, oversized-leaf isolation;
- trainer integration on the virtual 8-device mesh: overlap requires
  ``--zero1`` (fail-fast ValueError), master params + EMA CREATED
  data-axis-sharded (the fp32 tail all-gather disappears; the one
  master->compute cast is the per-bucket gather, half the bytes), the
  overlap trajectory tracking plain dp within the same tolerance the
  zero1-vs-dp test uses, and the checkpoint round-trip restoring
  SHARDED params bit-exactly.

The schedule-level certification (UL301/UL302 on the per-bucket
``param_gather``/``zero1_grads`` named scopes) lives in the Pass-4
auditor + ``tools/comms_baseline.json`` budgets; the end-to-end proof
vs a same-flags serial oracle is the ``tools/unicore_chaos.py
--comms-overlap`` CI leg.  This file is the fast tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from test_resilience import make_batch, make_trainer
from unicore_tpu import metrics
from unicore_tpu.distributed.utils import comm_bucket_assignment


# ---------------------------------------------------------------------
# bucket-assignment units
# ---------------------------------------------------------------------

def _tree(rng):
    return {
        "a": {"w": jnp.asarray(rng.randn(64, 64), jnp.float32),   # 16 KiB
              "b": jnp.asarray(rng.randn(64), jnp.float32)},      # 256 B
        "c": {"w": jnp.asarray(rng.randn(128, 64), jnp.float32)},  # 32 KiB
        "d": jnp.asarray(rng.randn(8), jnp.bfloat16),             # 16 B
    }


def test_bucket_assignment_every_leaf_exactly_one_bucket(rng):
    tree = _tree(rng)
    ids, n = comm_bucket_assignment(tree, 20 * 1024)
    id_leaves = jax.tree_util.tree_leaves(ids)
    # same structure: one integer id per leaf
    assert len(id_leaves) == len(jax.tree_util.tree_leaves(tree))
    assert all(isinstance(i, int) for i in id_leaves)
    # ids form a contiguous 0..n-1 range with no gaps (every bucket is
    # non-empty, every leaf lands in exactly one)
    assert set(id_leaves) == set(range(n))
    # the 32 KiB leaf exceeds the 20 KiB cap: isolated in its own bucket
    cw = ids["c"]["w"]
    assert sum(1 for i in id_leaves if i == cw) == 1


def test_bucket_assignment_deterministic_and_cap_scaling(rng):
    tree = _tree(rng)
    ids1, n1 = comm_bucket_assignment(tree, 20 * 1024)
    ids2, n2 = comm_bucket_assignment(tree, 20 * 1024)
    assert n1 == n2
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: a == b, ids1, ids2))
    # a cap larger than the whole tree collapses to one bucket; a tiny
    # cap isolates every leaf
    _, n_big = comm_bucket_assignment(tree, 1 << 30)
    _, n_tiny = comm_bucket_assignment(tree, 1)
    assert n_big == 1
    assert n_tiny == len(jax.tree_util.tree_leaves(tree))
    assert n_tiny >= n1 >= n_big


def test_bucket_assignment_respects_cap_for_fitting_leaves(rng):
    tree = _tree(rng)
    cap = 20 * 1024
    ids, n = comm_bucket_assignment(tree, cap)
    per_bucket = {}
    for (path, x), (_, i) in zip(
        jax.tree_util.tree_flatten_with_path(tree)[0],
        jax.tree_util.tree_flatten_with_path(ids)[0],
    ):
        nbytes = int(np.prod(x.shape, dtype=np.int64)) * x.dtype.itemsize
        per_bucket.setdefault(i, []).append(nbytes)
    for i, sizes in per_bucket.items():
        # a bucket only exceeds the cap when it holds a single
        # oversized leaf
        if sum(sizes) > cap:
            assert len(sizes) == 1


def test_bucket_assignment_empty_tree():
    ids, n = comm_bucket_assignment({}, 1024)
    assert n == 0 and jax.tree_util.tree_leaves(ids) == []


# ---------------------------------------------------------------------
# trainer integration (virtual 8-device dp mesh)
# ---------------------------------------------------------------------

def test_overlap_requires_zero1():
    with pytest.raises(ValueError, match="zero1"):
        make_trainer(comms_overlap=True)


def _data_sharded(leaf):
    axes = {a for e in leaf.sharding.spec if e
            for a in (e if isinstance(e, tuple) else (e,))}
    return "data" in axes


def test_overlap_params_created_data_sharded(rng):
    """Under overlap the MASTER params (and EMA) live data-sharded —
    the fp32 update runs on 1/N shards and only the bf16/compute gather
    materializes full weights."""
    metrics.reset()
    trainer = make_trainer(zero1=True, comms_overlap=True,
                           comms_bucket_mb=0.001, ema_decay=0.999)
    with metrics.aggregate("train"):
        trainer.train_step([make_batch(rng)])
        trainer.flush_stats()
    n_sharded = 0
    for leaf in jax.tree_util.tree_leaves(trainer.state["params"]):
        if leaf.ndim >= 1 and leaf.size % 8 == 0:
            assert _data_sharded(leaf), (leaf.shape, leaf.sharding.spec)
            n_sharded += 1
    assert n_sharded >= 2
    for leaf in jax.tree_util.tree_leaves(trainer.state["ema"]):
        if leaf.ndim >= 1 and leaf.size % 8 == 0:
            assert _data_sharded(leaf)
    # the tiny cap split the tree into several buckets
    assert trainer._comm_bucket_count >= 2
    # without the flag params stay fully replicated (overlap is opt-in;
    # the default zero1 layout is what test_zero1 asserts)
    metrics.reset()
    plain = make_trainer(zero1=True)
    with metrics.aggregate("train"):
        plain.train_step([make_batch(rng)])
        plain.flush_stats()
    for leaf in jax.tree_util.tree_leaves(plain.state["params"]):
        assert leaf.sharding.is_fully_replicated


def test_overlap_trajectory_tracks_dp(rng):
    """Bucketed constraints + the hoisted cast move WHERE collectives
    happen, not the math: same tolerance as the zero1-vs-dp test."""
    losses = {}
    for key, over in (
        ("dp", {}),
        ("overlap", {"zero1": True, "comms_overlap": True,
                     "comms_bucket_mb": 0.001}),
    ):
        metrics.reset()
        trainer = make_trainer(**over)
        brng = np.random.RandomState(3)
        got = []
        with metrics.aggregate("train"):
            for _ in range(6):
                logs = trainer.train_step([make_batch(brng)])
                if logs:
                    got.append(float(logs[0]["loss"]))
            trainer.flush_stats()
        losses[key] = np.asarray(got)
    np.testing.assert_allclose(losses["overlap"], losses["dp"], rtol=2e-4)


def test_overlap_checkpoint_roundtrip_sharded_params(rng, tmp_path):
    """Data-sharded master params ride the .shard files through a save
    and a dp-size-preserving restore bit-exactly, and come back
    SHARDED."""
    metrics.reset()
    trainer = make_trainer(zero1=True, comms_overlap=True)
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        for _ in range(3):
            trainer.train_step([batch])
        trainer.flush_stats()
    path = str(tmp_path / "ckpt_overlap.pt")
    trainer.save_checkpoint(path, {"train_iterator": {"epoch": 1}})
    want = jax.device_get(trainer.state)

    metrics.reset()
    fresh = make_trainer(zero1=True, comms_overlap=True)
    fresh.load_checkpoint(path)
    with metrics.aggregate("train"):
        fresh.init_state(batch)
    got = jax.device_get(fresh.state)
    flat_w, tree_w = jax.tree_util.tree_flatten(want)
    flat_g, tree_g = jax.tree_util.tree_flatten(got)
    assert tree_w == tree_g
    for a, b in zip(flat_w, flat_g):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert any(_data_sharded(l) for l in
               jax.tree_util.tree_leaves(fresh.state["params"])
               if l.ndim >= 1)
    # the restored run still steps and its bucket layout recomputed
    # identically (pure function of the param tree + cap)
    assert fresh._comm_bucket_count == trainer._comm_bucket_count
    with metrics.aggregate("train"):
        logs = fresh.train_step([batch])
    assert np.isfinite(logs[0]["loss"])
