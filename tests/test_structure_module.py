"""Structure-module (IPA) tests — the second half of the Uni-Fold
workload (BASELINE configs[2]).  The load-bearing property is
EQUIVARIANCE: applying one global rigid motion to every input frame must
leave the IPA output exactly unchanged (attention sees only
frame-relative geometry)."""

import jax
import jax.numpy as jnp
import numpy as np

from unicore_tpu.modules import (
    InvariantPointAttention,
    StructureModule,
    StructureModuleLayer,
)
from unicore_tpu.modules.structure_module import (
    identity_rigid,
    quat_to_rot,
    rigid_apply,
    rigid_compose,
    rigid_invert_apply,
)

B, R, C, H = 2, 12, 32, 4


def random_rigid(rng, shape):
    q = jnp.asarray(rng.randn(*shape, 4).astype(np.float32))
    rot = quat_to_rot(q)
    trans = jnp.asarray(rng.randn(*shape, 3).astype(np.float32) * 3.0)
    return rot, trans


def test_quat_to_rot_orthonormal(rng):
    q = jnp.asarray(rng.randn(5, 4).astype(np.float32))
    rot = np.asarray(quat_to_rot(q))
    for m in rot:
        np.testing.assert_allclose(m @ m.T, np.eye(3), atol=1e-5)
        assert np.linalg.det(m) > 0.99
    # identity quaternion -> identity rotation
    np.testing.assert_allclose(
        np.asarray(quat_to_rot(jnp.asarray([1.0, 0, 0, 0]))), np.eye(3),
        atol=1e-6,
    )


def test_rigid_invert_roundtrip(rng):
    rot, trans = random_rigid(rng, (B, R))
    pts = jnp.asarray(rng.randn(B, R, 5, 3).astype(np.float32))
    glob = rigid_apply(rot, trans, pts)
    back = rigid_invert_apply(rot, trans, glob)
    np.testing.assert_allclose(np.asarray(back), np.asarray(pts), atol=1e-4)


def test_rigid_compose_matches_sequential(rng):
    ra, ta = random_rigid(rng, (B, R))
    rb, tb = random_rigid(rng, (B, R))
    pts = jnp.asarray(rng.randn(B, R, 3).astype(np.float32))
    rc, tc = rigid_compose(ra, ta, rb, tb)
    np.testing.assert_allclose(
        np.asarray(rigid_apply(rc, tc, pts)),
        np.asarray(rigid_apply(ra, ta, rigid_apply(rb, tb, pts))),
        atol=1e-4,
    )


def test_ipa_global_rigid_invariance(rng):
    """Composing one global rigid motion onto every frame leaves the IPA
    output unchanged — the property that makes the module a structure
    module rather than a coordinate MLP."""
    s = jnp.asarray(rng.randn(B, R, C).astype(np.float32))
    z = jnp.asarray(rng.randn(B, R, R, C).astype(np.float32))
    rot, trans = random_rigid(rng, (B, R))
    mod = InvariantPointAttention(embed_dim=C, num_heads=H)
    params = mod.init(jax.random.PRNGKey(0), s, z, rot, trans)["params"]
    # zero-init out_proj would make any output invariant trivially;
    # perturb all params away from init first
    params = jax.tree_util.tree_map(
        lambda x: x + 0.05 * jnp.ones_like(x), params
    )
    out1 = mod.apply({"params": params}, s, z, rot, trans)

    g_rot, g_trans = random_rigid(rng, (1, 1))
    g_rot = jnp.broadcast_to(g_rot, rot.shape)
    g_trans = jnp.broadcast_to(g_trans, trans.shape)
    rot2, trans2 = rigid_compose(g_rot, g_trans, rot, trans)
    out2 = mod.apply({"params": params}, s, z, rot2, trans2)
    np.testing.assert_allclose(
        np.asarray(out1), np.asarray(out2), atol=2e-3
    )


def test_ipa_pair_values_are_pairwise(rng):
    """The pair-value term must gather z[b, q, k] per attention weight —
    a z perturbation that PRESERVES every row-sum over its second residue
    index but changes individual pairs must change the output (regression
    for a row-sum-collapsing einsum)."""
    s = jnp.asarray(rng.randn(B, R, C).astype(np.float32))
    z = rng.randn(B, R, R, C).astype(np.float32)
    rot, trans = random_rigid(rng, (B, R))
    mod = InvariantPointAttention(embed_dim=C, num_heads=H)
    params = mod.init(
        jax.random.PRNGKey(0), s, jnp.asarray(z), rot, trans
    )["params"]
    params = jax.tree_util.tree_map(
        lambda x: x + 0.05 * jnp.ones_like(x), params
    )
    # kill the pair-BIAS path so only the pair-VALUE gather sees z
    params["pair_bias"]["kernel"] = jnp.zeros_like(
        params["pair_bias"]["kernel"]
    )
    out1 = mod.apply({"params": params}, s, jnp.asarray(z), rot, trans)
    z2 = z.copy()
    z2[:, :, 0, :], z2[:, :, 1, :] = z[:, :, 1, :], z[:, :, 0, :]
    out2 = mod.apply({"params": params}, s, jnp.asarray(z2), rot, trans)
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_ipa_mask_cuts_contribution(rng):
    s = rng.randn(B, R, C).astype(np.float32)
    z = jnp.asarray(rng.randn(B, R, R, C).astype(np.float32))
    rot, trans = random_rigid(rng, (B, R))
    mask = np.ones((B, R), dtype=np.float32)
    mask[:, R - 2:] = 0.0
    mod = InvariantPointAttention(embed_dim=C, num_heads=H)
    params = mod.init(
        jax.random.PRNGKey(0), jnp.asarray(s), z, rot, trans,
        jnp.asarray(mask),
    )["params"]
    params = jax.tree_util.tree_map(
        lambda x: x + 0.05 * jnp.ones_like(x), params
    )
    out1 = mod.apply({"params": params}, jnp.asarray(s), z, rot, trans,
                     jnp.asarray(mask))
    s2 = s.copy()
    s2[:, R - 2:] += 50.0  # perturb ONLY masked residues' features
    out2 = mod.apply({"params": params}, jnp.asarray(s2), z, rot, trans,
                     jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(out1[:, : R - 2]), np.asarray(out2[:, : R - 2]),
        rtol=1e-4, atol=1e-4,
    )


def test_structure_module_fwd_bwd(rng):
    """Full module: N shared-weight iterations step fwd+bwd with finite
    grads into every param, and the frames move off identity."""
    s = jnp.asarray(rng.randn(B, R, C).astype(np.float32))
    z = jnp.asarray(rng.randn(B, R, R, C).astype(np.float32))
    mod = StructureModule(embed_dim=C, num_heads=H, n_layers=3)
    params = mod.init(jax.random.PRNGKey(0), s, z)["params"]
    params = jax.tree_util.tree_map(
        lambda x: x + 0.02 * jnp.ones_like(x), params
    )

    def loss(p):
        s_out, (rot, trans), pos = mod.apply({"params": p}, s, z)
        return jnp.sum(pos ** 2) + jnp.sum(s_out ** 2)

    val, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(val))
    flat = jax.tree_util.tree_leaves(g)
    assert flat and all(np.isfinite(np.asarray(l)).all() for l in flat)

    _, (rot, trans), pos = mod.apply({"params": params}, s, z)
    assert pos.shape == (B, R, 3)
    assert float(jnp.sum(jnp.abs(trans))) > 0  # backbone actually updated
    eye = identity_rigid((B, R))[0]
    assert float(jnp.sum(jnp.abs(rot - eye))) > 0
