"""Unit tests for checkpoint retention + hygiene fixes (VERDICT r3 item 8).

Covers: best-checkpoint pruning with negative metric values (the old
regex ``(\\d+\\.?\\d*)`` never matched ``-3.21`` so retention silently
kept everything), and the bottom-right causal-mask alignment.
"""

from argparse import Namespace

import numpy as np
import pytest

from unicore_tpu.checkpoint_utils import _prune, checkpoint_paths


def _retention_args(save_dir, keep_best, maximize):
    return Namespace(
        save_dir=save_dir,
        keep_interval_updates=0,
        keep_last_epochs=0,
        keep_best_checkpoints=keep_best,
        best_checkpoint_metric="loss",
        maximize_best_checkpoint_metric=maximize,
    )


def _touch(d, name):
    (d / name).write_bytes(b"x")


def test_keep_best_prunes_negative_values(tmp_path):
    # maximized metric (e.g. log-likelihood): best values are the LEAST
    # negative ones
    for v in ("-1.25", "-3.50", "-0.75", "-2.00"):
        _touch(tmp_path, f"checkpoint.best_loss_{v}.pt")
    args = _retention_args(str(tmp_path), keep_best=2, maximize=True)
    _prune(args, end_of_epoch=False)
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == [
        "checkpoint.best_loss_-0.75.pt",
        "checkpoint.best_loss_-1.25.pt",
    ]


def test_keep_best_prunes_minimized_mixed_sign(tmp_path):
    for v in ("-0.50", "0.25", "1.75", "-2.25"):
        _touch(tmp_path, f"checkpoint.best_loss_{v}.pt")
    args = _retention_args(str(tmp_path), keep_best=2, maximize=False)
    _prune(args, end_of_epoch=False)
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == [
        "checkpoint.best_loss_-0.50.pt",
        "checkpoint.best_loss_-2.25.pt",
    ]


def test_checkpoint_paths_scientific_notation(tmp_path):
    _touch(tmp_path, "checkpoint.best_loss_1.5e-03.pt")
    _touch(tmp_path, "checkpoint.best_loss_2.0e-03.pt")
    got = checkpoint_paths(
        str(tmp_path),
        pattern=r"checkpoint\.best_loss_(-?\d+\.?\d*(?:[eE][+-]?\d+)?)\.pt",
    )
    assert [g.split("_")[-1] for g in got] == ["2.0e-03.pt", "1.5e-03.pt"]


def test_adam_betas_literal_only():
    from unicore_tpu.optim.adam import UnicoreAdam

    opt = UnicoreAdam(Namespace(
        adam_betas="(0.9, 0.98)", adam_eps=1e-8, weight_decay=0.0, lr=[1e-3]
    ))
    assert (opt.beta1, opt.beta2) == (0.9, 0.98)
    with pytest.raises((ValueError, SyntaxError)):
        UnicoreAdam(Namespace(
            adam_betas="__import__('os').getcwd()", adam_eps=1e-8,
            weight_decay=0.0, lr=[1e-3],
        ))


def test_causal_mask_bottom_right_alignment():
    from unicore_tpu.utils import causal_iota_mask

    # square: ordinary triangle
    m = np.asarray(causal_iota_mask(4, 4))
    assert (m[0, 1:] < -1e20).all() and (np.diag(m) == 0).all()

    # tq < tk (incremental decode: queries are the LAST tq positions) —
    # query row i may see keys <= i + (tk - tq)
    m = np.asarray(causal_iota_mask(2, 5))
    assert (m[0, :4] == 0).all() and m[0, 4] < -1e20
    assert (m[1, :] == 0).all()
