"""Evoformer proof (BASELINE north star): the 5-D triangle-attention
contracts run end-to-end — module forward/backward AND a full Trainer
step over an EvoformerPairBlock model."""

import os
from argparse import Namespace

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from unicore_tpu import metrics
from unicore_tpu.losses.unicore_loss import UnicoreLoss
from unicore_tpu.models.unicore_model import BaseUnicoreModel
from unicore_tpu.modules import (
    EvoformerPairBlock,
    TriangleAttention,
    TriangleMultiplication,
)
from unicore_tpu.tasks.unicore_task import UnicoreTask
from unicore_tpu.trainer import Trainer

B, N, C, H = 2, 8, 32, 4

# On real TPU the einsum rides bf16 MXU lanes while the loop oracle
# accumulates in fp64 — tolerance must cover the lane rounding (same
# error model as tests/test_flash_attention.py).
_ON_TPU = os.environ.get("UNICORE_TPU_TEST_ON_TPU", "") == "1"
ORACLE_TOL = (
    dict(rtol=5e-2, atol=2e-3) if _ON_TPU else dict(rtol=2e-4, atol=2e-4)
)


def test_triangle_attention_shapes_and_mask(rng):
    z = jnp.asarray(rng.randn(B, N, N, C).astype(np.float32))
    mask = np.ones((B, N, N), dtype=np.float32)
    mask[:, :, N // 2:] = 0.0  # mask the right half of every row
    mod = TriangleAttention(embed_dim=C, num_heads=H, orientation="per_row")
    params = mod.init(jax.random.PRNGKey(0), z, jnp.asarray(mask))["params"]
    out = mod.apply({"params": params}, z, jnp.asarray(mask))
    assert out.shape == z.shape and np.isfinite(np.asarray(out)).all()
    # masked key columns must not influence the output: perturb them
    z2 = np.asarray(z).copy()
    z2[:, :, N // 2:, :] += 100.0
    out2 = mod.apply({"params": params}, jnp.asarray(z2), jnp.asarray(mask))
    # rows attend over columns; only the value/bias of VALID columns count,
    # so outputs at valid query positions change only via the bias path of
    # masked pairs — compare at valid columns with the pair_bias of masked
    # keys unchanged is intractable here; instead check the gradient wrt
    # masked keys' VALUE path is zero:
    def pooled(zz):
        o = mod.apply({"params": params}, zz, jnp.asarray(mask))
        return jnp.sum(o[:, :, : N // 2, :] ** 2)

    g = jax.grad(pooled)(z)
    # gradient flows into masked columns only through LN/bias/gate paths of
    # their own outputs (excluded above) — the attention VALUE path is cut,
    # so the gradient into masked keys is exactly the pair-bias path; with
    # softmax saturated by -1e9 those probs are ~0
    assert np.isfinite(np.asarray(g)).all()


def test_triangle_multiplication_contraction_oracle(rng):
    """The einsum contraction matches a per-edge numpy oracle in both
    directions (AlphaFold Alg. 11/12 semantics)."""
    z = jnp.asarray(rng.randn(B, N, N, C).astype(np.float32))
    for direction in ("outgoing", "incoming"):
        mod = TriangleMultiplication(embed_dim=C, direction=direction)
        params = mod.init(jax.random.PRNGKey(0), z)["params"]

        # reproduce the module's pre-contraction activations, then
        # contract with explicit loops as the oracle
        def pre(name, p=params):
            zn = nn.LayerNorm().apply(
                {"params": p["layer_norm_in"]}, z)
            proj = zn @ p[f"{name}_proj"]["kernel"]
            gate = jax.nn.sigmoid(
                zn @ p[f"{name}_gate"]["kernel"] + p[f"{name}_gate"]["bias"]
            )
            return np.asarray(proj * gate)

        a, b = pre("a"), pre("b")
        want = np.zeros_like(a)
        for i in range(N):
            for j in range(N):
                if direction == "outgoing":
                    want[:, i, j] = (a[:, i, :, :] * b[:, j, :, :]).sum(1)
                else:
                    want[:, i, j] = (a[:, :, i, :] * b[:, :, j, :]).sum(1)
        got = (
            jnp.einsum("bikc,bjkc->bijc", jnp.asarray(a), jnp.asarray(b))
            if direction == "outgoing"
            else jnp.einsum("bkic,bkjc->bijc", jnp.asarray(a), jnp.asarray(b))
        )
        np.testing.assert_allclose(np.asarray(got), want, **ORACLE_TOL)
        out = mod.apply({"params": params}, z)
        assert out.shape == z.shape and np.isfinite(np.asarray(out)).all()


def test_triangle_multiplication_mask_cuts_contribution(rng):
    """Masked edges must not contribute to any other edge's update."""
    z = rng.randn(B, N, N, C).astype(np.float32)
    mask = np.ones((B, N, N), dtype=np.float32)
    mask[:, :, N - 1] = 0.0  # mask the last column of every row
    mod = TriangleMultiplication(embed_dim=C, direction="outgoing")
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(z),
                      jnp.asarray(mask))["params"]
    out1 = mod.apply({"params": params}, jnp.asarray(z), jnp.asarray(mask))
    z2 = z.copy()
    z2[:, :, N - 1, :] += 50.0  # perturb ONLY masked edges
    out2 = mod.apply({"params": params}, jnp.asarray(z2), jnp.asarray(mask))
    # updates of UNMASKED edges are unchanged (masked edges' own rows may
    # differ through their zn/gates)
    np.testing.assert_allclose(
        np.asarray(out1[:, :, : N - 1]), np.asarray(out2[:, :, : N - 1]),
        rtol=1e-5, atol=1e-5,
    )


def test_evoformer_pair_block_grads(rng):
    z = jnp.asarray(rng.randn(B, N, N, C).astype(np.float32))
    mod = EvoformerPairBlock(embed_dim=C, num_heads=H)
    params = mod.init(jax.random.PRNGKey(0), z)["params"]

    def loss(p):
        return jnp.sum(mod.apply({"params": p}, z) ** 2)

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


class PairModel(BaseUnicoreModel):
    @nn.compact
    def __call__(self, pair, deterministic=True, **kw):
        z = nn.Dense(C, name="embed")(pair)
        z = EvoformerPairBlock(embed_dim=C, num_heads=H, dropout=0.1,
                               name="block")(z, deterministic=deterministic)
        return nn.Dense(1, name="head")(z)[..., 0]


class PairLoss(UnicoreLoss):
    """Regress the mean pair feature (dummy objective)."""

    def forward(self, model, params, sample, rng=None, is_training=True):
        pred = model.apply(
            {"params": params}, **sample["net_input"],
            deterministic=not is_training,
            rngs={"dropout": rng} if (is_training and rng is not None) else None,
        )
        target = sample["target"]
        loss = jnp.sum((pred - target) ** 2)
        n = jnp.asarray(np.prod(target.shape), dtype=jnp.float32)
        return loss, n, {"loss": loss, "sample_size": n}

    @staticmethod
    def reduce_metrics(logging_outputs, split="train"):
        loss = sum(float(l.get("loss", 0)) for l in logging_outputs)
        n = sum(float(l.get("sample_size", 0)) for l in logging_outputs)
        metrics.log_scalar("loss", loss / max(n, 1), n, round=3)

    @staticmethod
    def logging_outputs_can_be_summed(is_train):
        return True


class PairTask(UnicoreTask):
    pass


def test_evoformer_trainer_step_end_to_end(rng):
    """A full train step (grad-accum scan, clip, metrics) over a model
    whose attention is the 5-D triangle pattern — the BASELINE 'Evoformer
    step runs end-to-end on TPU' proof, CPU-checked here and compiled on
    real TPU by the driver via __graft_entry__."""
    args = Namespace(
        seed=1, update_freq=[1], clip_norm=1.0, ema_decay=-1.0,
        fp16=False, bf16=False, bf16_sr=False,
        optimizer="adam", lr=[1e-3], adam_betas="(0.9, 0.999)",
        adam_eps=1e-8, weight_decay=0.0,
        lr_scheduler="fixed", force_anneal=None, lr_shrink=0.1,
        warmup_updates=0, min_loss_scale=1e-4, fp16_scale_window=None,
        fp16_init_scale=4.0, max_update=10, max_epoch=0,
        tensor_parallel_size=1, seq_parallel_size=1, fsdp_size=1,
    )
    task = PairTask(args)
    trainer = Trainer(args, task, PairModel(), PairLoss(task))
    feats = rng.randn(8, N, N, 5).astype(np.float32)
    target = feats.mean(axis=-1)
    batch = {"net_input": {"pair": feats}, "target": target}
    metrics.reset()
    losses = []
    with metrics.aggregate("train"):
        for _ in range(8):
            logs = trainer.train_step([batch])
            losses.append(float(logs[0]["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # it learns
    assert trainer.get_num_updates() == 8


# ---------------------------------------------------------------------------
# MSA half (VERDICT r3 missing-2): row attention with pair bias, column
# attention, outer product mean, and the full EvoformerBlock
# ---------------------------------------------------------------------------

S = 4  # sequences


def test_group_flash_matches_materialized(rng):
    """At flash-eligible dims (T a 128-multiple) the triangle and MSA-row
    attentions must produce the same output through the grouped flash
    kernel (forced pallas backend) as through the materialized einsum +
    softmax path (reference backend) — the O(N^3)-memory blockwise route
    is a pure backend swap."""
    from unicore_tpu.modules import MSARowAttentionWithPairBias
    from unicore_tpu.ops.backend import kernel_backend

    n, c, heads = 128, 32, 4
    z = jnp.asarray(rng.randn(1, n, n, c).astype(np.float32))
    mask = np.ones((1, n, n), dtype=np.float32)
    mask[:, :, -17:] = 0.0
    mask = jnp.asarray(mask)

    tri = TriangleAttention(embed_dim=c, num_heads=heads, dropout=0.0)
    params = tri.init(jax.random.PRNGKey(0), z, mask)

    with kernel_backend("pallas"):
        out_flash = tri.apply(params, z, mask, True)
    with kernel_backend("reference"):
        out_ref = tri.apply(params, z, mask, True)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_ref), rtol=2e-2, atol=2e-3
    )

    # gradients flow through the kernel's dbias path into the pair-bias
    # projection (the bias is an activation here, not a parameter)
    def loss(p, backend):
        with kernel_backend(backend):
            return jnp.sum(tri.apply(p, z, mask, True) ** 2)

    g_flash = jax.grad(lambda p: loss(p, "pallas"))(params)
    g_ref = jax.grad(lambda p: loss(p, "reference"))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-3
        ),
        g_flash, g_ref,
    )

    s, cm = 4, 16
    msa = jnp.asarray(rng.randn(1, s, n, cm).astype(np.float32))
    msa_mask = jnp.asarray(np.ones((1, s, n), dtype=np.float32))
    row = MSARowAttentionWithPairBias(embed_dim=cm, num_heads=2, dropout=0.0)
    zsmall = jnp.asarray(rng.randn(1, n, n, 8).astype(np.float32))
    p2 = row.init(jax.random.PRNGKey(1), msa, zsmall, msa_mask)
    with kernel_backend("pallas"):
        o_flash = row.apply(p2, msa, zsmall, msa_mask, True)
    with kernel_backend("reference"):
        o_ref = row.apply(p2, msa, zsmall, msa_mask, True)
    np.testing.assert_allclose(
        np.asarray(o_flash), np.asarray(o_ref), rtol=2e-2, atol=2e-3
    )


def test_msa_row_attention_oracle(rng):
    """Row attention == explicit jnp composition (softmax over the last
    dim of scores + pair bias + mask), including the [B,1,H,R,R] bias and
    [B,S,1,1,R] mask broadcast contracts."""
    from unicore_tpu.modules import MSARowAttentionWithPairBias

    msa = jnp.asarray(rng.randn(B, S, N, C).astype(np.float32))
    z = jnp.asarray(rng.randn(B, N, N, C).astype(np.float32))
    mask = np.ones((B, S, N), dtype=np.float32)
    mask[:, :, N - 2:] = 0.0
    mod = MSARowAttentionWithPairBias(embed_dim=C, num_heads=H)
    params = mod.init(
        jax.random.PRNGKey(0), msa, z, jnp.asarray(mask)
    )["params"]
    out = mod.apply({"params": params}, msa, z, jnp.asarray(mask))
    assert out.shape == msa.shape and np.isfinite(np.asarray(out)).all()

    # oracle: rebuild from the params with explicit ops
    p = params
    m = nn.LayerNorm().apply({"params": p["layer_norm"]}, msa)
    head_dim = C // H

    def proj(name):
        y = m @ p[name]["kernel"]
        return y.reshape(B, S, N, H, head_dim)

    q, k, v = proj("q_proj"), proj("k_proj"), proj("v_proj")
    scores = jnp.einsum("bsqhd,bskhd->bshqk", q * head_dim ** -0.5, k)
    zn = nn.LayerNorm().apply({"params": p["pair_norm"]}, z)
    bias = jnp.transpose(zn @ p["pair_bias"]["kernel"], (0, 3, 1, 2))[:, None]
    add = jnp.where(jnp.asarray(mask).astype(bool), 0.0, -1e9)[:, :, None, None, :]
    probs = jax.nn.softmax(
        (scores + bias + add).astype(jnp.float32), axis=-1
    ).astype(scores.dtype)
    o = jnp.einsum("bshqk,bskhd->bsqhd", probs, v).reshape(B, S, N, C)
    gate = jax.nn.sigmoid(m @ p["gate"]["kernel"] + p["gate"]["bias"])
    want = (o * gate) @ p["out_proj"]["kernel"] + p["out_proj"]["bias"]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), **ORACLE_TOL
    )


def test_msa_column_attention_mask(rng):
    """Masked MSA rows must not influence valid rows' outputs (attention
    over sequences per residue column)."""
    from unicore_tpu.modules import MSAColumnAttention

    msa = rng.randn(B, S, N, C).astype(np.float32)
    mask = np.ones((B, S, N), dtype=np.float32)
    mask[:, S - 1, :] = 0.0  # last sequence row invalid
    mod = MSAColumnAttention(embed_dim=C, num_heads=H)
    params = mod.init(
        jax.random.PRNGKey(0), jnp.asarray(msa), jnp.asarray(mask)
    )["params"]
    out1 = mod.apply({"params": params}, jnp.asarray(msa), jnp.asarray(mask))
    msa2 = msa.copy()
    msa2[:, S - 1, :, :] += 100.0  # perturb ONLY the masked row
    out2 = mod.apply({"params": params}, jnp.asarray(msa2), jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(out1[:, : S - 1]), np.asarray(out2[:, : S - 1]),
        rtol=1e-5, atol=1e-5,
    )


def test_outer_product_mean_oracle(rng):
    """OPM == per-pair loop oracle, with the mask-count normalization."""
    from unicore_tpu.modules import OuterProductMean

    HID = 4
    msa = jnp.asarray(rng.randn(B, S, N, C).astype(np.float32))
    mask = (rng.rand(B, S, N) > 0.3).astype(np.float32)
    mod = OuterProductMean(pair_dim=C, hidden_dim=HID)
    params = mod.init(
        jax.random.PRNGKey(0), msa, jnp.asarray(mask)
    )["params"]
    out = mod.apply({"params": params}, msa, jnp.asarray(mask))
    assert out.shape == (B, N, N, C)

    p = params
    m = np.asarray(nn.LayerNorm().apply({"params": p["layer_norm"]}, msa))
    a = (m @ np.asarray(p["a_proj"]["kernel"])) * mask[..., None]
    b = (m @ np.asarray(p["b_proj"]["kernel"])) * mask[..., None]
    want = np.zeros((B, N, N, HID * HID), dtype=np.float32)
    for bi in range(B):
        for i in range(N):
            for j in range(N):
                outer = np.einsum("sc,sd->cd", a[bi, :, i], b[bi, :, j])
                norm = max(float((mask[bi, :, i] * mask[bi, :, j]).sum()), 1e-3)
                want[bi, i, j] = (outer / norm).reshape(-1)
    want = want @ np.asarray(p["out_proj"]["kernel"]) + np.asarray(
        p["out_proj"]["bias"]
    )
    np.testing.assert_allclose(np.asarray(out), want, **ORACLE_TOL)


def test_evoformer_block_fwd_bwd(rng):
    """The full block (MSA half + OPM + pair half) steps fwd+bwd with
    finite grads into every param — the 'Evoformer block steps fwd+bwd'
    done-condition of VERDICT r3 next-4."""
    from unicore_tpu.modules import EvoformerBlock

    msa = jnp.asarray(rng.randn(B, S, N, C).astype(np.float32))
    z = jnp.asarray(rng.randn(B, N, N, C).astype(np.float32))
    msa_mask = jnp.asarray(np.ones((B, S, N), dtype=np.float32))
    pair_mask = jnp.asarray(np.ones((B, N, N), dtype=np.float32))
    mod = EvoformerBlock(msa_dim=C, pair_dim=C, msa_heads=H, pair_heads=H)
    params = mod.init(
        jax.random.PRNGKey(0), msa, z, msa_mask, pair_mask
    )["params"]
    # perturb away from init: the zero-initialized output projections
    # (AlphaFold-style) make everything upstream of them zero-grad at
    # exactly step 0, which is init policy, not a dead submodule
    keys = jax.random.split(jax.random.PRNGKey(1), len(jax.tree_util.tree_leaves(params)))
    params = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params),
        [
            leaf + 0.02 * jax.random.normal(k, leaf.shape, leaf.dtype)
            for leaf, k in zip(jax.tree_util.tree_leaves(params), keys)
        ],
    )

    def loss(p):
        m2, z2 = mod.apply({"params": p}, msa, z, msa_mask, pair_mask)
        return jnp.sum(m2.astype(jnp.float32) ** 2) + jnp.sum(
            z2.astype(jnp.float32) ** 2
        )

    g = jax.grad(loss)(params)
    flat = jax.tree_util.tree_leaves(g)
    assert flat and all(np.isfinite(np.asarray(l)).all() for l in flat)
    # every parameter receives gradient (no dead submodule)
    dead = [
        "/".join(str(k.key) for k in path)
        for path, leaf in jax.tree_util.tree_leaves_with_path(g)
        if float(jnp.sum(jnp.abs(leaf))) == 0.0
    ]
    assert not dead, f"zero-grad params: {dead}"
