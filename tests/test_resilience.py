"""Fault-tolerance subsystem tests (unicore_tpu/resilience +
checkpoint_utils integrity layer).

The end-to-end SIGKILL/corrupt/resume proof lives in
``tools/unicore_chaos.py`` (run by CI; ``test_chaos_harness_*`` below is
the slow-marked pytest wrapper).  Everything here is the fast unit and
trainer-integration tier: guard math, escalation ladder, snapshot-ring
rewind, watchdog, preemption flag, checksum verification, and the
CheckpointManager restore edge cases (missing final marker, stale
scratch, checksum-mismatch fallback)."""

import os
import pickle
import signal
import time
from argparse import Namespace

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu import checkpoint_utils, metrics
from unicore_tpu.losses.unicore_loss import UnicoreLoss
from unicore_tpu.models.unicore_model import BaseUnicoreModel
from unicore_tpu.resilience import (
    AnomalyGuardConfig,
    EscalationPolicy,
    GracefulShutdown,
    SnapshotRing,
    StepWatchdog,
    guard_init,
    guard_update,
    read_trajectory,
    restore_state,
    snapshot_state,
)
from unicore_tpu.tasks.unicore_task import UnicoreTask
from unicore_tpu.trainer import Trainer

VOCAB, DIM = 13, 16


# ---------------------------------------------------------------------
# toy trainer (same shape as tests/test_trainer.py)
# ---------------------------------------------------------------------

class ToyModel(BaseUnicoreModel):
    @nn.compact
    def __call__(self, src_tokens, deterministic=True, **kwargs):
        x = nn.Embed(VOCAB, DIM, name="embed")(src_tokens)
        return nn.Dense(VOCAB, name="out")(x)


class ToyLoss(UnicoreLoss):
    def forward(self, model, params, sample, rng=None, is_training=True):
        logits = model.apply(
            {"params": params}, **sample["net_input"],
            deterministic=not is_training,
        )
        lprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        target = sample["target"]
        nll = -jnp.take_along_axis(lprobs, target[..., None], axis=-1)[..., 0]
        loss = jnp.sum(nll)
        n = jnp.asarray(np.prod(target.shape), dtype=jnp.float32)
        return loss, n, {"loss": loss, "sample_size": n}

    @staticmethod
    def reduce_metrics(logging_outputs, split="train"):
        loss = sum(float(l.get("loss", 0)) for l in logging_outputs)
        n = sum(float(l.get("sample_size", 0)) for l in logging_outputs)
        metrics.log_scalar("loss", loss / max(n, 1), n, round=3)

    @staticmethod
    def logging_outputs_can_be_summed(is_train):
        return True


class ToyTask(UnicoreTask):
    pass


def make_args(**over):
    d = dict(
        seed=1, update_freq=[1], clip_norm=0.0, ema_decay=-1.0,
        fp16=False, bf16=False, bf16_sr=False, stats_lag=0,
        optimizer="adam", lr=[1e-2], adam_betas="(0.9, 0.999)",
        adam_eps=1e-8, weight_decay=0.0,
        lr_scheduler="fixed", force_anneal=None, lr_shrink=0.1,
        warmup_updates=0, min_loss_scale=1e-4, fp16_scale_window=None,
        fp16_init_scale=4.0, max_update=100, max_epoch=0,
        tensor_parallel_size=1, seq_parallel_size=1, fsdp_size=1,
    )
    d.update(over)
    return Namespace(**d)


def make_trainer(**over):
    args = make_args(**over)
    task = ToyTask(args)
    return Trainer(args, task, ToyModel(), ToyLoss(task))


def make_batch(rng, bsz=8, seq=8):
    toks = rng.randint(0, VOCAB, size=(bsz, seq)).astype(np.int64)
    return {"net_input": {"src_tokens": toks}, "target": toks.copy()}


def poison_params(trainer):
    from unicore_tpu.distributed import replicated

    bad = jax.device_get(trainer.state["params"])
    bad["embed"]["embedding"] = np.full_like(
        bad["embed"]["embedding"], np.inf
    )
    trainer.state["params"] = jax.device_put(
        jax.tree_util.tree_map(jnp.asarray, bad), replicated(trainer.mesh)
    )


# ---------------------------------------------------------------------
# guard math (pure, on device scalars)
# ---------------------------------------------------------------------

def test_guard_spike_detection_and_warmup():
    cfg = AnomalyGuardConfig(spike_factor=3.0, window=8, warmup=4,
                             act_on_spike=True)
    g = guard_init()
    over = jnp.zeros((), bool)
    # warmup: even a huge jump must not fire before `warmup` clean steps
    for loss in (1.0, 1.01, 0.99):
        g, anomalous, spike = guard_update(g, jnp.float32(loss), over, cfg)
        assert not bool(spike) and not bool(anomalous)
    g, _, spike = guard_update(g, jnp.float32(100.0), over, cfg)
    assert not bool(spike), "fired during warmup"
    # the warmup-step outlier DID fold in; rebuild a tight baseline
    for loss in (1.0, 1.0, 1.01, 0.99, 1.0, 1.0, 1.0, 1.0):
        g, _, _ = guard_update(g, jnp.float32(loss), over, cfg)
    baseline = float(g["loss_ema"])
    g, anomalous, spike = guard_update(g, jnp.float32(1e4), over, cfg)
    assert bool(spike) and bool(anomalous)
    assert int(g["streak"]) == 1 and int(g["spikes"]) == 1
    # the anomalous loss must NOT drag the EMA
    assert float(g["loss_ema"]) == pytest.approx(baseline)
    # clean step resets the streak
    g, anomalous, _ = guard_update(g, jnp.float32(1.0), over, cfg)
    assert not bool(anomalous) and int(g["streak"]) == 0


def test_guard_detect_only_without_act_on_spike():
    cfg = AnomalyGuardConfig(spike_factor=3.0, window=8, warmup=2,
                             act_on_spike=False)
    g = guard_init()
    for loss in (1.0, 1.0, 1.0, 1.0):
        g, _, _ = guard_update(g, jnp.float32(loss), jnp.zeros((), bool), cfg)
    g, anomalous, spike = guard_update(
        g, jnp.float32(1e4), jnp.zeros((), bool), cfg
    )
    assert bool(spike) and not bool(anomalous)  # counted, not skipped
    # overflow still skips regardless of the flag
    g, anomalous, _ = guard_update(
        g, jnp.float32(1.0), jnp.ones((), bool), cfg
    )
    assert bool(anomalous)


def test_guard_nonfinite_loss_does_not_poison_ema():
    cfg = AnomalyGuardConfig(spike_factor=3.0, window=8, warmup=2)
    g = guard_init()
    for loss in (1.0, 1.0, 1.0):
        g, _, _ = guard_update(g, jnp.float32(loss), jnp.zeros((), bool), cfg)
    ema = float(g["loss_ema"])
    g, _, _ = guard_update(
        g, jnp.float32(np.nan), jnp.ones((), bool), cfg
    )
    assert float(g["loss_ema"]) == pytest.approx(ema)
    assert np.isfinite(float(g["loss_ema"]))


def test_guard_ema_tracks_decaying_loss():
    """The baseline is a WINDOWED ema, not an all-run mean: after a loss
    decay it must converge to the new level within ~window steps (an
    all-run mean would stay stranded between the two levels and let a
    genuine late-training spike hide under the inflated sigma)."""
    cfg = AnomalyGuardConfig(spike_factor=3.0, window=4, warmup=2)
    g = guard_init()
    over = jnp.zeros((), bool)
    for _ in range(20):
        g, _, _ = guard_update(g, jnp.float32(1.0), over, cfg)
    for _ in range(40):
        g, _, _ = guard_update(g, jnp.float32(0.0), over, cfg)
    assert float(g["loss_ema"]) < 0.01


def test_escalation_ladder_order():
    cfg = AnomalyGuardConfig(escalate=True, backoff_after=2,
                             rewind_after=3, abort_after=5)
    pol = EscalationPolicy(cfg, has_scaler=True, has_ring=True)
    assert pol.decide(False, 0) == "none"
    assert pol.decide(True, 1) == "skip"
    assert pol.decide(True, 2) == "backoff"
    # the backoff rung halves the fp16 loss scale — meaningless (and not
    # performed by the jitted step) for a finite loss spike, so a
    # spike-only streak skips there instead
    assert pol.decide(True, 2, overflow=False) == "skip"
    assert pol.decide(True, 3) == "rewind"
    assert pol.decide(True, 5) == "abort"
    # no ring: the rewind stage is unreachable, backoff holds until abort
    pol2 = EscalationPolicy(cfg, has_scaler=True, has_ring=False)
    assert pol2.decide(True, 4) == "backoff"
    # no scaler either: skip only
    pol3 = EscalationPolicy(cfg, has_scaler=False, has_ring=False)
    assert pol3.decide(True, 4) == "skip"
    # legacy mode (no --anomaly-guard): always plain skip
    pol4 = EscalationPolicy(
        AnomalyGuardConfig(escalate=False), has_scaler=True, has_ring=True
    )
    assert pol4.decide(True, 99) == "skip"


# ---------------------------------------------------------------------
# trainer integration: skip / rewind / abort
# ---------------------------------------------------------------------

def test_injected_nonfinite_grad_skips_without_poisoning_state(
        rng, monkeypatch):
    """Acceptance criterion: an injected nonfinite gradient is skipped
    without touching optimizer state, and metrics record the stage."""
    monkeypatch.setenv("UNICORE_TPU_CHAOS_INJECT", "nonfinite:1")
    metrics.reset()
    trainer = make_trainer(anomaly_guard=True)
    batch = make_batch(rng)
    with metrics.aggregate("train") as agg:
        trainer.train_step([batch])           # dispatch 0: clean
        before = jax.device_get(
            {"params": trainer.state["params"],
             "opt_state": trainer.state["opt_state"]}
        )
        n_before = trainer.get_num_updates()
        trainer.train_step([batch])           # dispatch 1: poisoned grads
        after = jax.device_get(
            {"params": trainer.state["params"],
             "opt_state": trainer.state["opt_state"]}
        )
        # skipped: no update count, params AND moments bit-identical
        assert trainer.get_num_updates() == n_before
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(after)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(jax.device_get(trainer.state["guard"]["skips"])) == 1
        assert int(jax.device_get(trainer.state["guard"]["streak"])) == 1
        # the escalation stage landed in metrics
        smoothed = agg.get_smoothed_values()
        assert smoothed.get("anomaly_skip", 0) >= 1
        # next step is clean again: streak resets, training continues
        logs = trainer.train_step([batch])
        assert np.isfinite(logs[0]["loss"])
        assert trainer.get_num_updates() == n_before + 1
        assert int(jax.device_get(trainer.state["guard"]["streak"])) == 0


def test_escalation_rewind_restores_last_good_state(rng):
    metrics.reset()
    trainer = make_trainer(
        anomaly_guard=True, snapshot_interval_updates=1,
        snapshot_ring_size=2, anomaly_rewind_after=2, anomaly_abort_after=6,
    )
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])
        trainer.train_step([batch])
    assert len(trainer._snapshot_ring) == 2
    good = jax.device_get(trainer.state["params"])
    poison_params(trainer)
    with metrics.aggregate("train"):
        trainer.train_step([batch])   # streak 1: skip (params stay poisoned)
        assert trainer.get_num_updates() == 2
        trainer.train_step([batch])   # streak 2: REWIND to last-good
    restored = jax.device_get(trainer.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(good),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert trainer.get_num_updates() == 2
    assert trainer._escalation.rewinds == 1
    # and the run keeps training cleanly from the restored state
    with metrics.aggregate("train"):
        logs = trainer.train_step([batch])
    assert np.isfinite(logs[0]["loss"])
    assert trainer.get_num_updates() == 3


def test_rewind_streak_carries_to_abort(rng):
    """A persistent fault must not loop skip->rewind forever: the
    anomaly streak carries ACROSS a rewind (the snapshot was taken on a
    clean step with streak 0), so --anomaly-abort-after stays a real
    bound on consecutive anomalies."""
    metrics.reset()
    trainer = make_trainer(
        anomaly_guard=True, snapshot_interval_updates=1,
        snapshot_ring_size=2, anomaly_rewind_after=2, anomaly_abort_after=3,
    )
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])
        trainer.train_step([batch])
        poison_params(trainer)
        trainer.train_step([batch])   # streak 1: skip
        trainer.train_step([batch])   # streak 2: rewind (streak carried)
        assert trainer._escalation.rewinds == 1
        assert int(jax.device_get(trainer.state["guard"]["streak"])) == 2
        poison_params(trainer)        # the fault persists past the rewind
        with pytest.raises(FloatingPointError, match="escalation exhausted"):
            trainer.train_step([batch])  # streak 3: abort, not rewind again


def test_escalation_abort_after_threshold(rng):
    metrics.reset()
    trainer = make_trainer(anomaly_guard=True, anomaly_abort_after=2)
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])
        poison_params(trainer)
        trainer.train_step([batch])  # streak 1: skip
        with pytest.raises(FloatingPointError, match="escalation exhausted"):
            trainer.train_step([batch])  # streak 2: abort


def test_legacy_nonscaler_abort_preserved(rng):
    """Without --anomaly-guard, bf16/fp32 still aborts on the FIRST
    non-finite step (the pre-resilience contract)."""
    metrics.reset()
    trainer = make_trainer()
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        trainer.train_step([batch])
        poison_params(trainer)
        with pytest.raises(FloatingPointError, match="Non-finite gradients"):
            trainer.train_step([batch])


def test_injected_spike_skips_update(rng, monkeypatch):
    monkeypatch.setenv("UNICORE_TPU_CHAOS_INJECT", "spike:4")
    metrics.reset()
    trainer = make_trainer(
        anomaly_guard=True, loss_spike_factor=3.0, loss_spike_window=8,
        loss_spike_warmup=2,
    )
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        for _ in range(4):
            trainer.train_step([batch])      # dispatches 0-3: clean
        n = trainer.get_num_updates()
        before = jax.device_get(trainer.state["params"])
        trainer.train_step([batch])          # dispatch 4: spiked loss stat
        after = jax.device_get(trainer.state["params"])
    assert trainer.get_num_updates() == n   # skipped
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(jax.device_get(trainer.state["guard"]["spikes"])) == 1


def test_resume_with_skip_is_bit_exact(rng, tmp_path, monkeypatch):
    """dispatch_count persistence: a run with an anomaly skip before the
    checkpoint resumes onto the IDENTICAL dropout streams, so the
    continuation is bit-exact vs the uninterrupted run."""
    monkeypatch.setenv("UNICORE_TPU_CHAOS_INJECT", "nonfinite:1")
    metrics.reset()
    batches = [make_batch(rng) for _ in range(6)]
    t1 = make_trainer(anomaly_guard=True)
    with metrics.aggregate("train"):
        for b in batches[:4]:
            t1.train_step([b])  # dispatch 1 is skipped -> 3 updates
    assert t1.get_num_updates() == 3
    fn = os.path.join(str(tmp_path), "ckpt.pt")
    t1.save_checkpoint(fn, {"train_iterator": {"epoch": 1}})

    t2 = make_trainer(anomaly_guard=True)
    t2.load_checkpoint(fn)
    t2.init_state(batches[0])
    assert t2._dispatch_count == 4  # restored verbatim, skip included
    with metrics.aggregate("train"):
        for b in batches[4:]:
            t1.train_step([b])
            t2.train_step([b])
    p1 = jax.device_get(t1.state["params"])
    p2 = jax.device_get(t2.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------
# snapshot ring / watchdog / preemption / trajectory units
# ---------------------------------------------------------------------

def test_snapshot_ring_roundtrip():
    state = {
        "step": jnp.int32(7),
        "w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
    }
    snap = snapshot_state(state)
    state["w"] = state["w"] * 0 - 1.0  # diverge the live state
    back = restore_state(snap)
    assert int(back["step"]) == 7
    np.testing.assert_array_equal(
        np.asarray(back["w"]), np.arange(12, dtype=np.float32).reshape(3, 4)
    )
    ring = SnapshotRing(size=2)
    for u in (1, 2, 3):
        ring.take(state, u, u)
    assert len(ring) == 2
    assert ring.latest()[0] == 3  # newest survives, oldest evicted


def test_watchdog_fires_and_disarms():
    fired = []
    dog = StepWatchdog(0.15, on_timeout=lambda phase, t: fired.append(phase))
    with dog.armed("fast-phase"):
        time.sleep(0.01)
    time.sleep(0.4)
    assert fired == [], "fired although the phase finished in time"
    try:
        with dog.armed("slow-phase"):
            time.sleep(0.6)
        assert fired == ["slow-phase"]
        assert dog.fired
    finally:
        dog.close()


def test_graceful_shutdown_flag_and_uninstall():
    shutdown = GracefulShutdown(signals=(signal.SIGTERM,)).install()
    try:
        assert not shutdown.requested
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)
        assert shutdown.requested and shutdown.signum == signal.SIGTERM
    finally:
        shutdown.uninstall()


def test_trajectory_writer_roundtrip_and_torn_line(tmp_path):
    from unicore_tpu.resilience import TrajectoryWriter

    path = str(tmp_path / "traj.jsonl")
    w = TrajectoryWriter(path)
    w.record(update=1, dispatch=0, loss=1.0 / 3.0, skipped=False,
             action="none")
    w.record(update=2, dispatch=1, loss=2.0 / 3.0, skipped=False,
             action="none")
    w.close()
    with open(path, "a") as f:
        f.write('{"update": 3, "dispa')  # SIGKILL mid-write
    records = read_trajectory(path)
    assert len(records) == 2
    assert records[0]["loss"] == 1.0 / 3.0  # exact float round trip


# ---------------------------------------------------------------------
# checkpoint integrity + CheckpointManager restore edge cases
# ---------------------------------------------------------------------

def test_atomic_save_writes_final_marker_and_verifies(tmp_path):
    p = str(tmp_path / "c.pt")
    checkpoint_utils.atomic_save({"x": 1}, p)
    assert os.path.exists(p + ".sum")
    assert checkpoint_utils.file_integrity(p) == "ok"
    assert pickle.loads(checkpoint_utils.read_verified(p)) == {"x": 1}


def test_read_verified_detects_corruption(tmp_path):
    p = str(tmp_path / "c.pt")
    checkpoint_utils.atomic_save({"x": list(range(100))}, p)
    data = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(data[:10] + bytes([data[10] ^ 0xFF]) + data[11:])
    assert checkpoint_utils.file_integrity(p) == "torn"
    with pytest.raises(checkpoint_utils.CheckpointIntegrityError):
        checkpoint_utils.read_verified(p, retries=2, backoff=0.01)


def test_read_verified_retries_transient_io(tmp_path, monkeypatch):
    p = str(tmp_path / "c.pt")
    checkpoint_utils.atomic_save({"x": 1}, p)
    real_open = open
    fails = {"n": 1}

    def flaky_open(path, *a, **kw):
        if str(path) == p and fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("transient NFS hiccup")
        return real_open(path, *a, **kw)

    import builtins

    monkeypatch.setattr(builtins, "open", flaky_open)
    payload = checkpoint_utils.read_verified(p, retries=3, backoff=0.01)
    assert pickle.loads(payload) == {"x": 1}
    assert fails["n"] == 0


def _manager_args(tmp_path, **over):
    d = dict(
        save_dir=str(tmp_path / "save"),
        tmp_save_dir=str(tmp_path / "scratch"),
        no_save=False, save_interval=1, save_interval_updates=0,
        keep_interval_updates=-1, keep_last_epochs=-1,
        keep_best_checkpoints=-1, best_checkpoint_metric="loss",
        maximize_best_checkpoint_metric=False, no_epoch_checkpoints=False,
        no_last_checkpoints=False, checkpoint_suffix="",
        restore_file="checkpoint_last.pt", finetune_from_model=None,
        reset_optimizer=False, reset_lr_scheduler=False, reset_meters=False,
        reset_dataloader=False, optimizer_overrides="{}",
    )
    d.update(over)
    os.makedirs(d["save_dir"], exist_ok=True)
    os.makedirs(d["tmp_save_dir"], exist_ok=True)
    return Namespace(**d)


class _StubTrainer:
    """Duck-typed trainer for CheckpointManager.restore: records which
    checkpoint actually loaded and propagates integrity errors exactly
    like the real ``Trainer.load_checkpoint`` read path."""

    def __init__(self):
        self.loaded_path = None

    def load_checkpoint(self, path, *a, **kw):
        if not checkpoint_utils.checkpoint_exists(path):
            return None
        state = checkpoint_utils.load_checkpoint_to_cpu(path)
        self.loaded_path = path
        return state["extra_state"]

    def get_train_iterator(self, epoch, load_dataset=True, **kw):
        class _Itr:
            def __init__(self):
                self.epoch = epoch

            def load_state_dict(self, sd):
                pass

        return _Itr()

    def init_total_train_steps(self, epoch_itr):
        pass

    def lr_step(self, epoch):
        pass


class _SaveItr:
    """Save-side epoch_itr stand-in for CheckpointManager.save."""

    epoch = 1

    def end_of_epoch(self):
        return False

    def state_dict(self):
        return {"epoch": 1}


def _saver_trainer(w):
    """A _StubTrainer that also owns a saveable state (``w``: the params
    payload) — the save-side half of the CheckpointManager contract."""

    class _SaverTrainer(_StubTrainer):
        is_data_parallel_master = True

        def get_num_updates(self):
            return 3

        def collect_checkpoint_state(self, extra_state):
            sd = {
                "model": {"params": {"w": w}},
                "optimizer_history": [{"num_updates": 3}],
                "extra_state": dict(extra_state),
            }
            return sd, []

    return _SaverTrainer()


def _write_round(save_dir, updates, names):
    payload = {
        "model": {"params": {"w": np.arange(updates, dtype=np.float32)}},
        "optimizer_history": [{"num_updates": updates}],
        "extra_state": {"train_iterator": {"epoch": 1}, "updates": updates},
    }
    for name in names:
        checkpoint_utils.atomic_save(payload, os.path.join(save_dir, name))


def test_manager_falls_back_on_checksum_mismatch(tmp_path):
    args = _manager_args(tmp_path)
    _write_round(args.save_dir, 3, ["checkpoint_1_3.pt"])
    time.sleep(0.02)
    _write_round(args.save_dir, 6, ["checkpoint_1_6.pt", "checkpoint_last.pt"])
    # tear the newest round (both names — restore must reach round 3)
    for name in ("checkpoint_last.pt", "checkpoint_1_6.pt"):
        p = os.path.join(args.save_dir, name)
        data = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(data[:-8] + b"DEADBEEF")
    mgr = checkpoint_utils.CheckpointManager(args, is_master=True)
    trainer = _StubTrainer()
    extra, _ = mgr.restore(trainer)
    assert extra["updates"] == 3
    assert trainer.loaded_path.endswith("checkpoint_1_3.pt")
    mgr.close()


def test_manager_falls_back_on_missing_final_marker(tmp_path):
    """A save that died between the data rename and the .sum rename (or a
    half-copied finalize) leaves a torn file without a trustworthy
    marker; restore must fall back to the previous intact round."""
    args = _manager_args(tmp_path)
    _write_round(args.save_dir, 3, ["checkpoint_1_3.pt"])
    time.sleep(0.02)
    _write_round(args.save_dir, 6, ["checkpoint_last.pt"])
    last = os.path.join(args.save_dir, "checkpoint_last.pt")
    os.remove(last + ".sum")           # final marker never landed...
    data = open(last, "rb").read()
    with open(last, "wb") as f:
        f.write(data[:len(data) // 2])  # ...because the copy was torn
    mgr = checkpoint_utils.CheckpointManager(args, is_master=True)
    trainer = _StubTrainer()
    extra, _ = mgr.restore(trainer)
    assert extra["updates"] == 3
    assert trainer.loaded_path.endswith("checkpoint_1_3.pt")
    mgr.close()


def test_manager_explicit_restore_file_fails_loudly(tmp_path):
    """--restore-file names ONE checkpoint; if it is torn the run must
    not silently train from some other state."""
    other = str(tmp_path / "elsewhere")
    os.makedirs(other)
    p = os.path.join(other, "model.pt")
    checkpoint_utils.atomic_save({"model": {}, "extra_state": {}}, p)
    data = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(data[:-4] + b"XXXX")
    args = _manager_args(tmp_path, restore_file=p)
    _write_round(args.save_dir, 3, ["checkpoint_last.pt"])  # tempting twin
    mgr = checkpoint_utils.CheckpointManager(args, is_master=True)
    with pytest.raises(checkpoint_utils.CheckpointIntegrityError):
        mgr.restore(_StubTrainer())
    mgr.close()


def test_manager_sweeps_stale_scratch(tmp_path):
    args = _manager_args(tmp_path)
    scratch = args.tmp_save_dir
    # torn data file (mismatched marker) — a crash mid-_finalize
    torn = os.path.join(scratch, "checkpoint_1_9.pt")
    checkpoint_utils.atomic_save({"x": 1}, torn)
    with open(torn, "ab") as f:
        f.write(b"GARBAGE")
    # interrupted atomic_save temp
    with open(os.path.join(scratch, "checkpoint_1_9.pt.tmp"), "wb") as f:
        f.write(b"partial")
    # INTACT scratch file (crash after write, before copy): must survive
    ok = os.path.join(scratch, "checkpoint_1_12.pt")
    checkpoint_utils.atomic_save({"x": 2}, ok)

    mgr = checkpoint_utils.CheckpointManager(args, is_master=True)
    assert not os.path.exists(torn)
    assert not os.path.exists(torn + ".sum")
    assert not os.path.exists(torn + ".tmp")
    assert os.path.exists(ok) and os.path.exists(ok + ".sum")
    mgr.close()


def test_shard_integrity_error_propagates(tmp_path):
    """A torn .shard file raises CheckpointIntegrityError from
    load_shard_entries — the signal the restore fallback consumes."""
    main = str(tmp_path / "c.pt")
    checkpoint_utils.write_checkpoint(
        {"model": {}}, {"params/w": [(((0, 2),), np.zeros(2))]},
        main, is_master=True, process_index=0, shard_token="tok",
    )
    shard = checkpoint_utils.shard_file(main, 0)
    data = open(shard, "rb").read()
    with open(shard, "wb") as f:
        f.write(data[:-4] + b"XXXX")
    with pytest.raises(checkpoint_utils.CheckpointIntegrityError):
        checkpoint_utils.load_shard_entries(main, 0, token="tok")


def test_missing_shard_sidecar_in_integrity_round_is_torn(tmp_path):
    """The SIGKILL-mid-finalize window the chaos harness caught: the
    shard's data copy landed but its .sum never did, and the bytes then
    rotted.  A rot that only flips float payload still unpickles, so
    the pre-integrity compat path ("no sidecar -> load unverified")
    would silently install garbage weights — when the round's MAIN file
    proves the writer was integrity-aware, a sidecar-less shard must be
    treated as torn instead."""
    main = str(tmp_path / "c.pt")
    checkpoint_utils.write_checkpoint(
        {"model": {}}, {"params/w": [(((0, 2),), np.zeros(2))]},
        main, is_master=True, process_index=0, shard_token="tok",
    )
    shard = checkpoint_utils.shard_file(main, 0)
    os.remove(shard + ".sum")  # the marker never landed
    with pytest.raises(checkpoint_utils.CheckpointIntegrityError):
        checkpoint_utils.load_shard_entries(main, 0, token="tok")
    assert checkpoint_utils.file_integrity(shard) == "torn"
    # the REVERSE window (main's marker missing, shard's landed) is the
    # same signature seen from the other sibling
    checkpoint_utils.write_checkpoint(
        {"model": {}}, {"params/w": [(((0, 2),), np.zeros(2))]},
        main, is_master=True, process_index=0, shard_token="tok",
    )
    os.remove(main + ".sum")
    with pytest.raises(checkpoint_utils.CheckpointIntegrityError):
        checkpoint_utils.load_checkpoint_to_cpu(main)
    # a round with NO sidecars at all stays loadable (pre-integrity
    # checkpoints must not break)
    lone = str(tmp_path / "legacy.pt")
    checkpoint_utils.atomic_save({"model": {}, "extra_state": {}}, lone)
    os.remove(lone + ".sum")
    assert checkpoint_utils.load_checkpoint_to_cpu(lone) is not None


# ---------------------------------------------------------------------
# async checkpoint writer (unit tier: the end-to-end crash-window proof
# is the chaos harness's kill-during-background-write legs)
# ---------------------------------------------------------------------

def test_writer_bounded_queue_backpressure():
    """submit() BLOCKS once max_queue jobs are in flight — a slow disk
    stalls the step path instead of piling state copies up in host
    memory — and the wait is counted."""
    import threading

    from unicore_tpu.resilience import AsyncCheckpointWriter

    gate = threading.Event()
    w = AsyncCheckpointWriter(max_queue=2)
    w.submit(gate.wait, label="job0")   # occupies the worker
    w.submit(lambda: None, label="job1")  # fills the queue
    t0 = time.monotonic()
    release = threading.Timer(0.25, gate.set)
    release.start()
    try:
        waited = w.submit(lambda: None, label="job2")  # must block
    finally:
        gate.set()
        release.cancel()
    assert waited >= 0.1, f"submit returned in {waited:.3f}s — no backpressure"
    assert time.monotonic() - t0 >= 0.1
    assert w.stats["backpressure_waits"] == 1
    w.close(drain=True)
    assert w.stats["completed"] == 3


def test_writer_failure_surfaces_at_next_poll_not_swallowed():
    """A failed background write re-raises on the MAIN thread at the
    next poll() — never silently (UL107's contract for the async
    path) — and later polls stay clean once surfaced."""
    from unicore_tpu.resilience import (
        AsyncCheckpointWriter,
        CheckpointWriteError,
    )

    w = AsyncCheckpointWriter(max_queue=2)

    def boom():
        raise OSError("disk on fire")

    w.submit(boom, label="checkpoint_1_3.pt")
    w.drain()
    with pytest.raises(CheckpointWriteError, match="checkpoint_1_3.pt"):
        w.poll()
    w.poll()  # surfaced once; the queue is clean again
    w.submit(lambda: None, label="ok")
    w.close(drain=True)
    w.poll()
    assert w.stats["failed"] == 1 and w.stats["completed"] == 1


def test_writer_drain_on_close_lands_queued_saves_in_order():
    """close(drain=True) — the preemption exit-0 gate — blocks until
    every submitted job has landed, in FIFO order."""
    from unicore_tpu.resilience import AsyncCheckpointWriter

    landed = []
    w = AsyncCheckpointWriter(max_queue=4)
    for i in range(4):
        w.submit(lambda i=i: (time.sleep(0.02), landed.append(i)),
                 label=f"job{i}")
    w.close(drain=True, raise_on_failure=True)
    assert landed == [0, 1, 2, 3]
    assert w.in_flight() == 0
    with pytest.raises(RuntimeError):
        w.submit(lambda: None)  # closed writers refuse new work


def test_writer_capture_ownership_and_wait_released():
    """owns()/wait_released(): the rewind interlock — a snapshot the
    writer is still serializing must not be reinstalled (and then
    donated) until its job lands."""
    import threading

    from unicore_tpu.resilience import AsyncCheckpointWriter

    capture = {"params": np.zeros(4)}
    gate = threading.Event()
    w = AsyncCheckpointWriter(max_queue=2)
    w.submit(gate.wait, label="hold", owned=(capture,))
    assert w.owns(capture)
    release = threading.Timer(0.15, gate.set)
    release.start()
    waited = w.wait_released(capture, timeout=5.0)
    assert not w.owns(capture)
    assert waited >= 0.05
    w.close(drain=True)
    # unknown objects are never owned
    assert not w.owns(object())


def test_writer_wait_released_times_out():
    import threading

    from unicore_tpu.resilience import AsyncCheckpointWriter

    capture = object()
    gate = threading.Event()
    w = AsyncCheckpointWriter(max_queue=1)
    w.submit(gate.wait, label="hold", owned=(capture,))
    with pytest.raises(TimeoutError):
        w.wait_released(capture, timeout=0.1)
    gate.set()
    w.close(drain=True)


def test_trainer_rewind_drains_inflight_writer(rng, monkeypatch):
    """The anomaly-guard rewind must serialize against an in-flight
    background save: reinstalling (then donating) host buffers the
    writer still reads would rot the checkpoint mid-pickle."""
    import threading

    from unicore_tpu.resilience import AsyncCheckpointWriter

    trainer = make_trainer(
        anomaly_guard=True, snapshot_interval_updates=1,
        snapshot_ring_size=2,
    )
    batch = make_batch(rng)
    with metrics.aggregate("train"):
        for _ in range(3):
            trainer.train_step([batch])
    trainer.flush_stats()
    assert len(trainer._snapshot_ring) > 0

    gate = threading.Event()
    w = AsyncCheckpointWriter(max_queue=2)
    trainer.attach_checkpoint_writer(w)
    w.submit(gate.wait, label="inflight")
    release = threading.Timer(0.2, gate.set)
    release.start()
    t0 = time.monotonic()
    with metrics.aggregate("train"):
        trainer._rewind_to_snapshot()   # must block on the writer first
    assert time.monotonic() - t0 >= 0.1, "rewind did not wait for the writer"
    assert w.in_flight() == 0
    w.close(drain=True)
    trainer.close()


def test_manager_async_save_failure_raises_on_poll(tmp_path, monkeypatch):
    """CheckpointManager end to end: a background write that fails
    surfaces from poll() (the train loop's step-boundary call), and the
    sync fallback (--async-save off) raises inline from save()."""
    from unicore_tpu.resilience import CheckpointWriteError

    def fail_write(*a, **kw):
        raise OSError("injected write failure")

    monkeypatch.setattr(checkpoint_utils, "write_checkpoint", fail_write)

    args = _manager_args(tmp_path, save_interval_updates=3,
                         async_save="on", save_queue_size=2,
                         no_epoch_checkpoints=True)
    mgr = checkpoint_utils.CheckpointManager(args, is_master=True)
    mgr.save(_saver_trainer(np.zeros(2, np.float32)), _SaveItr(), None,
             do_save=True)
    mgr.writer.drain()
    with pytest.raises(CheckpointWriteError):
        mgr.poll()
    mgr.close()

    args_sync = _manager_args(tmp_path, save_interval_updates=3,
                              async_save="off",
                              no_epoch_checkpoints=True,
                              save_dir=str(tmp_path / "save2"),
                              tmp_save_dir=str(tmp_path / "scratch2"))
    mgr = checkpoint_utils.CheckpointManager(args_sync, is_master=True)
    assert mgr.writer is None
    with pytest.raises(OSError):
        mgr.save(_saver_trainer(np.zeros(2, np.float32)), _SaveItr(),
                 None, do_save=True)
    mgr.close()


def test_manager_async_save_lands_and_restores(tmp_path):
    """The happy path: an async save streams to its final names (data +
    .sum marker) after drain, and restore() loads it."""
    args = _manager_args(tmp_path, save_interval_updates=3,
                         async_save="on", no_epoch_checkpoints=True)
    mgr = checkpoint_utils.CheckpointManager(args, is_master=True)
    mgr.save(_saver_trainer(np.arange(2, dtype=np.float32)), _SaveItr(),
             None, do_save=True)
    mgr.drain()  # the exit-0 gate: blocks until the files land, raises on failure
    last = os.path.join(args.save_dir, "checkpoint_last.pt")
    assert os.path.exists(last) and os.path.exists(last + ".sum")
    assert checkpoint_utils.file_integrity(last) == "ok"
    trainer = _StubTrainer()
    extra, _ = mgr.restore(trainer)
    assert trainer.loaded_path.endswith("checkpoint_last.pt")
    mgr.close()


# ---------------------------------------------------------------------
# chaos harness (slow: full subprocess training runs; CI runs the tool
# directly with the corrupt + inject legs)
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_harness_sigkill_resume(tmp_path):
    import tools.unicore_chaos as chaos

    rc = chaos.main([
        "--workdir", str(tmp_path / "chaos"), "--max-update", "8",
        "--save-interval-updates", "3", "--keep",
    ])
    assert rc == 0


@pytest.mark.slow
def test_chaos_harness_kill_during_background_write(tmp_path):
    """SIGKILL lands between the data copy and the .sum copy of an
    in-flight BACKGROUND write: the stale-marker checkpoint_last must be
    discriminated as torn and resume must fall back bit-exactly."""
    import tools.unicore_chaos as chaos

    rc = chaos.main([
        "--workdir", str(tmp_path / "chaos"), "--max-update", "10",
        "--save-interval-updates", "3", "--kill-in-write", "--keep",
    ])
    assert rc == 0


@pytest.mark.slow
def test_chaos_harness_sigterm_during_background_write(tmp_path):
    """SIGTERM while the writer holds an in-flight save: graceful
    shutdown must drain it (exit 0, every file intact) and the resume
    must be bit-exact."""
    import tools.unicore_chaos as chaos

    rc = chaos.main([
        "--workdir", str(tmp_path / "chaos"), "--max-update", "10",
        "--save-interval-updates", "3", "--kill-in-write", "--graceful",
        "--keep",
    ])
    assert rc == 0


@pytest.mark.slow
def test_chaos_harness_writer_io_failure(tmp_path):
    """An injected IO failure in a background write must bring the run
    down loudly (CheckpointWriteError at the next step boundary) and the
    resume from the last intact checkpoint must be bit-exact."""
    import tools.unicore_chaos as chaos

    rc = chaos.main([
        "--workdir", str(tmp_path / "chaos"), "--max-update", "10",
        "--save-interval-updates", "3", "--writer-fail", "2", "--keep",
    ])
    assert rc == 0
