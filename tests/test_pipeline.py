"""Multi-step pipelined dispatch (``--pipeline-depth K``) tests.

The contract under test (docs/performance.md#pipelined-dispatch): K=1 is
byte-identical to the classic loop; K>=2 keeps K dispatched steps in
flight, drains guard scalars and metrics lag-K (only outputs already on
host), and stays BIT-EXACT against the serial trajectory — including
through the anomaly ladder's rewind, which discards in-flight dispatches
issued past the anomaly and replays their staged batches under the same
dispatch ids.  The end-to-end chaos proof (SIGKILL/SIGTERM at K=2 vs a
K=1 oracle) lives in ``tools/unicore_chaos.py --pipeline-depth 2``; this
file is the fast unit/integration tier."""

import jax
import numpy as np
import pytest

from test_resilience import make_batch, make_trainer
from unicore_tpu import metrics
from unicore_tpu.resilience import read_trajectory


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def _params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _run(batches, *, traj=None, **over):
    """Feed ``batches`` one group per train_step call; returns
    (per-processing-order losses, trainer).  A pipelined call can retire
    SEVERAL steps (timing decides how many outputs the opportunistic
    drain finds ready), so consume every returned log entry — steps
    never returned twice, so no dedup is needed."""
    metrics.reset()
    trainer = make_trainer(trajectory_file=traj, **over)
    losses = []
    with metrics.aggregate("train"):
        for b in batches:
            out = trainer.train_step([b])
            losses.extend(float(o["loss"]) for o in out or ())
        out = trainer.flush_stats()
        losses.extend(float(o["loss"]) for o in out or ())
        smoothed = dict(metrics.get_smoothed_values("train"))
    trainer.close()
    return losses, trainer, smoothed


# ---------------------------------------------------------------------
# trajectory equivalence
# ---------------------------------------------------------------------

def test_k2_bit_identical_to_serial(rng):
    """The acceptance core: the pipelined run's losses, update count,
    params, and guard state are bit-identical to the strict serial
    (K=1, lag 0) run — pipelining moves host reads, never math."""
    batches = [make_batch(rng) for _ in range(8)]
    l1, t1, _ = _run(batches, pipeline_depth=1, stats_lag=0)
    l2, t2, _ = _run(batches, pipeline_depth=2)
    l3, t3, _ = _run(batches, pipeline_depth=3)
    assert l1 == l2 == l3
    assert (t1.get_num_updates() == t2.get_num_updates()
            == t3.get_num_updates() == len(batches))
    _params_equal(jax.device_get(t1.state["params"]),
                  jax.device_get(t2.state["params"]))
    _params_equal(jax.device_get(t1.state["params"]),
                  jax.device_get(t3.state["params"]))
    g1 = jax.device_get(t1.state["guard"])
    g2 = jax.device_get(t2.state["guard"])
    assert all(np.array_equal(g1[k], g2[k]) for k in g1)


def test_lag_k_metric_totals_match_serial(rng):
    """Lag-K drains defer WHEN a step's scalars are logged, never what:
    after the final flush the aggregated meters must agree with the
    serial run's exactly (the sum-over-run contract)."""
    batches = [make_batch(rng) for _ in range(6)]
    _, _, m1 = _run(batches, pipeline_depth=1, stats_lag=0)
    _, _, m2 = _run(batches, pipeline_depth=2)
    assert set(m1) == set(m2)
    for k in m1:
        if k in ("ups", "wall"):  # wall-clock meters, not step scalars
            continue
        assert m1[k] == pytest.approx(m2[k]), k


# ---------------------------------------------------------------------
# in-flight ring invariants
# ---------------------------------------------------------------------

def test_inflight_ring_invariants(rng):
    batches = [make_batch(rng) for _ in range(7)]
    metrics.reset()
    trainer = make_trainer(pipeline_depth=3)
    seen_ids = []
    with metrics.aggregate("train"):
        for b in batches:
            trainer.train_step([b])
            # never more than K dispatched-but-undrained steps...
            assert len(trainer._pending_stats) <= trainer.pipeline_depth
            # ...every entry holds its staged batch (rewind replay) and
            # ids stay strictly increasing
            for e in trainer._pending_stats:
                assert e[4] is not None
            ids = [e[3] for e in trainer._pending_stats]
            assert ids == sorted(ids)
            seen_ids.extend(ids)
            # every pulled group was dispatched before the call returned
            assert trainer._replay_queue == []
        trainer.flush_stats()
    assert trainer._pending_stats == []
    assert trainer.get_num_updates() == len(batches)
    assert trainer.retired_steps == len(batches)
    assert trainer._dispatch_count == len(batches)
    trainer.close()


def test_k1_ring_holds_no_batches(rng):
    """K=1 keeps the classic loop: ring entries do not pin their staged
    batches (no extra device-memory retention) and the drain-wait
    accounting stays untouched."""
    batches = [make_batch(rng) for _ in range(3)]
    metrics.reset()
    trainer = make_trainer(pipeline_depth=1, stats_lag=1)
    with metrics.aggregate("train"):
        for b in batches:
            trainer.train_step([b])
            for e in trainer._pending_stats:
                assert e[4] is None
        trainer.flush_stats()
    assert trainer.host_timers["drain_waits"] == 0
    trainer.close()


def test_boundary_accounting_excludes_drain_waits(rng):
    """At K>=2 the blocking lag-K fetch is device-bound wait, counted
    under drain_wait_s and EXCLUDED from step_boundary_host_s."""
    batches = [make_batch(rng) for _ in range(6)]
    _, trainer, _ = _run(batches, pipeline_depth=2)
    ht = trainer.host_timers
    assert ht["drain_waits"] > 0
    assert ht["drain_wait_s"] >= 0.0
    assert ht["step_boundaries"] > 0
    assert ht["step_boundary_host_s"] >= 0.0


# ---------------------------------------------------------------------
# anomaly ladder with K in flight
# ---------------------------------------------------------------------

def test_rewind_depth_k_bit_identical(rng, monkeypatch, tmp_path):
    """An injected nonfinite gradient escalates straight to rewind
    (rewind_after=1): at K=2 the in-flight dispatch issued past the
    anomaly is discarded, its staged batch replays under the SAME
    dispatch id from the restored state, and the whole trajectory —
    per-dispatch losses, actions, updates — plus the final params are
    bit-identical to the serial run's."""
    monkeypatch.setenv("UNICORE_TPU_CHAOS_INJECT", "nonfinite:4")
    batches = [make_batch(rng) for _ in range(9)]
    over = dict(
        anomaly_guard=True, snapshot_interval_updates=1,
        snapshot_ring_size=2, anomaly_rewind_after=1,
        anomaly_backoff_after=99, anomaly_abort_after=12,
    )
    t1 = str(tmp_path / "serial.jsonl")
    t2 = str(tmp_path / "pipelined.jsonl")
    _, tr1, _ = _run(batches, traj=t1, pipeline_depth=1, stats_lag=0,
                     **over)
    _, tr2, _ = _run(batches, traj=t2, pipeline_depth=2, **over)
    r1, r2 = read_trajectory(t1), read_trajectory(t2)
    assert len(r1) == len(r2) == len(batches)
    for a, b in zip(r1, r2):
        assert a == b
    assert [r["action"] for r in r1].count("rewind") == 1
    # the dispatch counter rewound over the discarded in-flight step and
    # advanced again through the replay: both runs end at the same count
    assert tr1._dispatch_count == tr2._dispatch_count == len(batches)
    _params_equal(jax.device_get(tr1.state["params"]),
                  jax.device_get(tr2.state["params"]))
    # ladder totals unchanged: the discarded dispatch never hit metrics
    g1 = jax.device_get(tr1.state["guard"])
    g2 = jax.device_get(tr2.state["guard"])
    for k in ("streak", "skips", "spikes"):
        assert int(g1[k]) == int(g2[k])


def test_snapshot_capture_exact_at_k2(rng):
    """Snapshots under pipelining must capture the state after exactly
    their recorded update (nothing newer in flight) — bit-identical to
    the serial run's ring entry."""
    batches = [make_batch(rng) for _ in range(6)]
    over = dict(anomaly_guard=True, snapshot_interval_updates=2,
                snapshot_ring_size=2)
    _, t1, _ = _run(batches, pipeline_depth=1, stats_lag=0, **over)
    _, t2, _ = _run(batches, pipeline_depth=2, **over)
    e1, e2 = t1._snapshot_ring.latest(), t2._snapshot_ring.latest()
    assert e1 is not None and e2 is not None
    assert e1[0] == e2[0]  # num_updates tag
    assert e1[1] == e2[1]  # dispatch tag
    from unicore_tpu.resilience import restore_state

    _params_equal(jax.device_get(restore_state(e1[2])["params"]),
                  jax.device_get(restore_state(e2[2])["params"]))


# ---------------------------------------------------------------------
# preemption / checkpoint invariants
# ---------------------------------------------------------------------

def test_preemption_flush_counts_every_pulled_group(rng, monkeypatch):
    """The iterator-position contract at K=2: a boundary flush (what a
    preemption checkpoint rides) leaves every pulled group dispatched
    and processed — dispatch_count == groups pulled, so a resume
    re-pulls exactly the groups this run never dispatched.  Holds
    through a rewind (replays reuse ids, not fresh pulls)."""
    monkeypatch.setenv("UNICORE_TPU_CHAOS_INJECT", "nonfinite:3")
    batches = [make_batch(rng) for _ in range(7)]
    metrics.reset()
    trainer = make_trainer(
        pipeline_depth=2, anomaly_guard=True,
        snapshot_interval_updates=1, snapshot_ring_size=2,
        anomaly_rewind_after=1, anomaly_backoff_after=99,
        anomaly_abort_after=12,
    )
    pulled = 0
    with metrics.aggregate("train"):
        for b in batches:
            pulled += 1
            trainer.train_step([b])
            assert trainer._replay_queue == []
        # the preemption boundary: flush, then capture
        trainer.flush_stats()
        sd = trainer.state_dict()
    hist = sd["optimizer_history"][0]
    assert hist["dispatch_count"] == pulled
    assert trainer._pending_stats == [] and trainer._replay_queue == []
    # one dispatch was anomalous (rewound), so updates trail by the
    # skip-free accounting — but every pulled batch WAS dispatched
    assert hist["num_updates"] == trainer.get_num_updates()
    trainer.close()


def test_rewind_during_flush_redispatches_stranded_replays(
        rng, monkeypatch):
    """A rewind can fire while a BOUNDARY flush drains the ring (not
    inside train_step): the discarded in-flight batches land on the
    replay queue with the dispatch counter rewound.  flush_stats must
    re-dispatch and drain them before returning — otherwise a
    checkpoint written at that boundary records a dispatch_count behind
    the iterator position and the resume silently skips a batch."""
    monkeypatch.setenv("UNICORE_TPU_CHAOS_INJECT", "nonfinite:3")
    metrics.reset()
    trainer = make_trainer(
        pipeline_depth=3, anomaly_guard=True,
        # ring present (decide() needs has_ring) but the interval never
        # crosses, so the pipelined sync-snapshot path stays out of the
        # way; the last-good entry is seeded manually below
        snapshot_interval_updates=1000, snapshot_ring_size=2,
        anomaly_rewind_after=1, anomaly_backoff_after=99,
        anomaly_abort_after=12,
    )
    # force every drain to the blocking path so the anomalous dispatch
    # is still IN the ring when flush_stats runs (the toy steps retire
    # fast enough that opportunistic drains would race the setup)
    monkeypatch.setattr(type(trainer), "_stats_ready",
                        staticmethod(lambda stats: False))
    batches = [make_batch(rng) for _ in range(5)]
    with metrics.aggregate("train"):
        trainer.train_step([batches[0]])
        trainer.train_step([batches[1]])
        trainer.flush_stats()
        assert trainer.get_num_updates() == 2
        trainer._snapshot_ring.take(
            trainer.state, 2, trainer._dispatch_count)
        # ids 2, 3 (poisoned), 4: the poisoned step and one dispatched
        # PAST it sit un-drained in the ring...
        trainer.train_step([batches[2]])
        trainer.train_step([batches[3]])
        trainer.train_step([batches[4]])
        assert len(trainer._pending_stats) >= 2
        # ...and the boundary flush hits the rewind mid-drain
        trainer.flush_stats()
        sd = trainer.state_dict()
    assert trainer._replay_queue == []
    assert trainer._pending_stats == []
    # every pulled group was (re-)dispatched: counts realigned
    assert sd["optimizer_history"][0]["dispatch_count"] == len(batches)
    # serial-oracle accounting: d2 landed (3), the rewind rolled back
    # to the snapshot (2), and the replayed d4 landed clean (3) —
    # exactly what a K=1 run of the same injection produces
    assert trainer.get_num_updates() == 3
    trainer.close()


def test_watchdog_context_names_inflight_depth(rng):
    batches = [make_batch(rng) for _ in range(2)]
    metrics.reset()
    trainer = make_trainer(pipeline_depth=3)
    with metrics.aggregate("train"):
        for b in batches:
            trainer.train_step([b])
        ctx = trainer._watchdog_context()
        # the live count depends on how fast the device retired the toy
        # steps; the dump must name the depth format either way
        assert "pipeline in_flight=" in ctx and "/3" in ctx
        trainer.flush_stats()
        assert "in_flight=0/3" in trainer._watchdog_context()
    trainer.close()
