"""unicore-lint Pass 5 (determinism) + runtime harness (ISSUE 19).

Static rules get the fire/silent/suppression treatment every other pass
gets: UL401 on synthetic HLO text, UL402 on text pairs plus a real
double-lower identity check on the dp mesh, UL403 on AST fixtures, and
the UL117 source-lint satellite on wall-clock fixture files.  The repo
sweeps (planning modules, decision-path source files) are pinned clean
so any regression names the exact new finding.  The runtime harness is
exercised both green (healthy jitted step double-runs bit-exact) and
red (a trace-time-gated pure_callback divergence must be localized to
the right primitive by the digest-stream bisector).
"""

import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unicore_tpu.analysis.determinism_audit import (
    DEFAULT_UL401_WHITELIST,
    PLANNING_MODULES,
    audit_determinism_text,
    audit_planning_modules,
    audit_planning_source,
    audit_program_identity,
)
from unicore_tpu.analysis.source_lint import lint_paths


def rules_of(findings):
    return {f.rule for f in findings}


def _repo_root():
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# UL401 nondeterministic-execution signatures (synthetic HLO)
# ---------------------------------------------------------------------

def test_ul401_fires_on_colliding_scatter():
    hlo = textwrap.dedent("""\
        HloModule grad_step
        ENTRY main {
          %p0 = f32[128,64]{1,0} parameter(0)
          %upd = f32[128,64]{1,0} scatter(%p0, %idx, %u),
            update_window_dims={1}, unique_indices=false
          ROOT %r = f32[128,64]{1,0} add(%upd, %p0)
        }
    """)
    found, stats = audit_determinism_text(hlo, context="fixture/grad")
    assert "UL401" in rules_of(found)
    assert stats["scatter"] == 1 and stats["scatter_whitelisted"] == 0
    assert any("fixture/grad" in f.location for f in found)


def test_ul401_silent_on_unique_indices_scatter():
    hlo = (
        "  %upd = f32[128,64] scatter(%p0, %idx, %u), "
        "unique_indices=true, to_apply=%add\n"
    )
    found, stats = audit_determinism_text(hlo, context="s")
    assert found == []
    assert stats["scatter_unique"] == 1


def test_ul401_whitelist_admits_slot_mapping_scatter():
    # the known-safe shape: KV writes routed by slot_mapping are
    # collision-free by construction even when the compiler can't
    # prove unique_indices
    hlo = (
        '  %w = f32[64,8,16] scatter(%pages, %slots, %kv), '
        'metadata={op_name="serve/kv_cache/slot_mapping_write"}\n'
    )
    found, stats = audit_determinism_text(hlo, context="s")
    assert found == []
    assert stats["scatter_whitelisted"] == 1
    # without the whitelist the same line is a finding
    found, _ = audit_determinism_text(hlo, context="s", whitelist=())
    assert "UL401" in rules_of(found)


def test_ul401_fires_on_unstable_sort():
    hlo = "  %s = (f32[8,97], s32[8,97]) sort(%logits, %iota), dimensions={1}\n"
    found, stats = audit_determinism_text(hlo, context="s")
    assert "UL401" in rules_of(found)
    assert stats["sort"] == 1 and stats["sort_stable"] == 0


def test_ul401_silent_on_stable_sort():
    hlo = (
        "  %s = (f32[8,97], s32[8,97]) sort(%logits, %iota), "
        "dimensions={1}, is_stable=true\n"
    )
    found, stats = audit_determinism_text(hlo, context="s")
    assert found == []
    assert stats["sort_stable"] == 1


def test_ul401_fires_on_non_threefry_rng():
    hlo = (
        "  %r = (u64[2], u32[8,128]) rng-bit-generator(u64[2] %state), "
        "algorithm=rng_philox\n"
    )
    found, _ = audit_determinism_text(hlo, context="s")
    assert "UL401" in rules_of(found)
    # threefry is counter-based and bit-reproducible: silent
    ok = (
        "  %r = (u64[2], u32[8,128]) rng-bit-generator(u64[2] %state), "
        "algorithm=rng_three_fry\n"
    )
    found, stats = audit_determinism_text(ok, context="s")
    assert found == []
    assert stats["rng"] == 1


def test_ul401_fires_on_stateful_rng():
    hlo = "  %r = f32[8] rng(%lo, %hi), distribution=rng_uniform\n"
    found, _ = audit_determinism_text(hlo, context="s")
    assert "UL401" in rules_of(found)


# ---------------------------------------------------------------------
# UL402 program identity
# ---------------------------------------------------------------------

def test_ul402_silent_on_identical_text():
    text = "HloModule m\nENTRY main { ROOT %r = f32[] add(%a, %b) }\n"
    found, stats = audit_program_identity(text, text, context="s")
    assert found == []
    assert stats["identical"] is True
    assert stats["program_bytes"] == len(text)


def test_ul402_names_first_differing_line():
    a = "HloModule m\n%x = f32[] add(%a, %b)\n%y = f32[] mul(%x, %x)\n"
    b = "HloModule m\n%x = f32[] add(%b, %a)\n%y = f32[] mul(%x, %x)\n"
    found, stats = audit_program_identity(a, b, context="s")
    assert rules_of(found) == {"UL402"}
    assert stats["identical"] is False
    assert stats["first_diff_line"] == 2
    assert "add(%a, %b)" in found[0].message


@pytest.mark.slow
def test_ul402_double_lower_identity_on_dp_mesh():
    # the property the committed scenarios rely on, demonstrated on a
    # real sharded program: two independent lower+compile cycles of
    # the same function in one process emit byte-identical text
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    sh = NamedSharding(mesh, P("dp", None))

    def step(x, w):
        return jnp.tanh(x @ w).sum(axis=-1)

    x = jax.ShapeDtypeStruct((64, 32), jnp.float32, sharding=sh)
    w = jax.ShapeDtypeStruct((32, 32), jnp.float32)
    texts = [
        jax.jit(step).lower(x, w).compile().as_text() for _ in range(2)
    ]
    found, stats = audit_program_identity(texts[0], texts[1], context="dp")
    assert found == [], [f.render() for f in found]
    assert stats["identical"] is True and stats["program_bytes"] > 0


# ---------------------------------------------------------------------
# UL403 host planning-code audit (AST fixtures)
# ---------------------------------------------------------------------

def test_ul403_fires_on_unsorted_set_iteration():
    found = audit_planning_source(textwrap.dedent("""\
        def plan(rows):
            live = {r.seq_id for r in rows}
            for sid in live:
                assign(sid)
    """), "serve/scheduler.py")
    assert rules_of(found) == {"UL403"}
    assert "set-iteration" in found[0].name


def test_ul403_silent_on_sorted_set_iteration():
    found = audit_planning_source(textwrap.dedent("""\
        def plan(rows):
            live = {r.seq_id for r in rows}
            for sid in sorted(live):
                assign(sid)
            order = [s for s in sorted(live | {0})]
    """), "serve/scheduler.py")
    assert found == []


def test_ul403_fires_on_salted_hash():
    found = audit_planning_source(textwrap.dedent("""\
        def route(key, n):
            return hash(key) % n
    """), "fleet/router.py")
    assert rules_of(found) == {"UL403"}
    assert "salted-hash" in found[0].name


def test_ul403_fires_on_id_in_ordering():
    found = audit_planning_source(textwrap.dedent("""\
        def tiebreak(a, b):
            return min(a, b, key=lambda s: id(s))
    """), "serve/kv_pool.py")
    assert rules_of(found) == {"UL403"}
    assert "id-in-ordering" in found[0].name
    # membership identity checks are fine: id() only matters when it
    # feeds an ordering decision
    found = audit_planning_source(textwrap.dedent("""\
        def seen(s, pool):
            return id(s) in pool
    """), "serve/kv_pool.py")
    assert found == []


def test_ul403_fires_on_wall_clock_and_honors_timing_idiom():
    found = audit_planning_source(textwrap.dedent("""\
        import time
        def admit(row):
            if time.time() > row.deadline:
                return False
            return True
    """), "serve/scheduler.py")
    assert rules_of(found) == {"UL403"}
    assert "wall-clock" in found[0].name
    # measuring elapsed time (t1 - t0) is not a planning decision
    found = audit_planning_source(textwrap.dedent("""\
        import time
        def trace(row):
            t0 = time.perf_counter()
            work(row)
            return time.perf_counter() - t0
    """), "serve/scheduler.py")
    assert found == []


def test_ul403_suppression_comment():
    found = audit_planning_source(textwrap.dedent("""\
        def route(key, n):
            return hash(key) % n  # unicore-lint: disable=UL403
    """), "fleet/router.py")
    assert found == []


def test_ul403_repo_planning_sweep_clean():
    # satellite 2: the shipped planning modules are Pass-5-clean with
    # zero suppressions.  A regression here names the exact finding.
    found, report = audit_planning_modules(_repo_root())
    assert found == [], "\n".join(f.render() for f in found)
    assert report["missing"] == []
    assert len(report["audited"]) == len(PLANNING_MODULES)


# ---------------------------------------------------------------------
# UL117 wall-clock in decision paths (source-lint satellite)
# ---------------------------------------------------------------------

def _lint_snippet(tmp_path, name, code):
    f = tmp_path / name
    f.write_text(textwrap.dedent(code))
    return lint_paths([str(f)])


def test_ul117_fires_on_wall_clock_decision(tmp_path):
    found = _lint_snippet(tmp_path, "my_scheduler.py", """
        import time
        def admit(row, deadline):
            return time.monotonic() < deadline
    """)
    assert "UL117" in rules_of(found)


def test_ul117_silent_on_timing_and_injectable_clock(tmp_path):
    found = _lint_snippet(tmp_path, "my_scheduler.py", """
        import time
        def probe(clock=None):
            clock = clock or time.monotonic
            t0 = time.perf_counter()
            work()
            elapsed = time.perf_counter() - t0
            return clock(), elapsed
    """)
    assert "UL117" not in rules_of(found)


def test_ul117_scope_and_suppression(tmp_path):
    # non-decision files are out of scope entirely
    found = _lint_snippet(tmp_path, "data_reader.py", """
        import time
        def shard(key):
            return time.time()
    """)
    assert "UL117" not in rules_of(found)
    found = _lint_snippet(tmp_path, "my_router.py", """
        import time
        def pick(ring):
            return ring[int(time.time())]  # unicore-lint: disable=UL117
    """)
    assert "UL117" not in rules_of(found)


def test_ul117_repo_decision_paths_clean():
    import os

    from unicore_tpu.analysis.cli import DEFAULT_LINT_ROOTS
    from unicore_tpu.analysis.findings import load_baseline, split_baselined

    root = _repo_root()
    roots = [os.path.join(root, d) for d in DEFAULT_LINT_ROOTS]
    findings = [
        f for f in lint_paths(roots, rel_to=root) if f.rule == "UL117"
    ]
    fps = load_baseline(os.path.join(root, "tools", "lint_baseline.json"))
    new, _ = split_baselined(findings, fps)
    assert new == [], "\n".join(f.render() for f in new)


# ---------------------------------------------------------------------
# runtime harness: bit-compare + digest-stream bisector
# ---------------------------------------------------------------------

def _harness():
    import importlib.util
    import os

    path = os.path.join(_repo_root(), "tools", "unicore_determinism.py")
    spec = importlib.util.spec_from_file_location(
        "unicore_determinism", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bitwise_compare_is_nan_safe_and_names_leaves():
    ud = _harness()
    a = {"w": np.array([1.0, np.nan], np.float32),
         "b": np.zeros(4, np.int32)}
    b = {"w": np.array([1.0, np.nan], np.float32),
         "b": np.zeros(4, np.int32)}
    mism, nbytes, leaves = ud.bitwise_compare(a, b)
    assert mism == [] and leaves == 2 and nbytes == 8 + 16
    b["w"] = np.array([1.0, 2.0], np.float32)
    mism, _, _ = ud.bitwise_compare(a, b)
    assert len(mism) == 1 and "w" in mism[0][0]


def test_double_run_bit_exact_on_healthy_jitted_step():
    ud = _harness()

    @jax.jit
    def step(w, x):
        h = jnp.tanh(x @ w)
        return {"loss": (h ** 2).sum(), "grad_ish": h.T @ x}

    rng = np.random.RandomState(3)
    args = (rng.randn(16, 8).astype(np.float32),
            rng.randn(32, 16).astype(np.float32))
    outs, ms = ud.double_run(step, args, runs=2)
    mism, nbytes, leaves = ud.bitwise_compare(outs[0], outs[1])
    assert mism == [] and leaves == 2 and nbytes > 0
    assert len(ms) == 2


def test_bisector_localizes_injected_divergence():
    ud = _harness()
    counter = {"n": 0}

    def drift(v):
        # trace-time-gated: pure only in name — each host execution
        # returns a different value, modeling an impure callback
        counter["n"] += 1
        return (v + np.float32(counter["n"])).astype(np.float32)

    def noisy(x):
        y = jnp.sin(x)          # eqn 0: deterministic prefix
        z = jax.pure_callback(
            drift, jax.ShapeDtypeStruct(x.shape, jnp.float32), y
        )
        return jnp.sum(z * 2.0)

    x = np.ones((4, 4), np.float32)
    fd = ud.first_divergence(jax.make_jaxpr(noisy)(x), [x])
    assert fd is not None
    assert "callback" in fd["primitive"]
    # the deterministic sin prefix must NOT be blamed
    assert fd["eqn_index"] > 0


def test_bisector_returns_none_on_deterministic_jaxpr():
    ud = _harness()

    def clean(x, w):
        return jnp.tanh(x @ w).sum()

    x = np.ones((8, 4), np.float32)
    w = np.ones((4, 4), np.float32)
    assert ud.first_divergence(jax.make_jaxpr(clean)(x, w), [x, w]) is None


def test_digest_stream_rejects_arity_mismatch():
    ud = _harness()

    def f(x):
        return x + 1.0

    closed = jax.make_jaxpr(f)(np.ones(3, np.float32))
    with pytest.raises(ValueError, match="leaves"):
        ud.digest_stream(closed, [])


@pytest.mark.slow
def test_harness_serve_surface_bit_exact():
    # end-to-end: capture a real ragged dispatch from the demo engine
    # and double-run it (the CI smoke runs the train surface too; here
    # we keep tier-"slow" wall time to the cheap engine)
    ud = _harness()
    report = ud.run_serve(runs=2)
    assert report["deterministic"] is True, report
    assert report["leaves"] >= 3 and report["bytes_compared"] > 0
