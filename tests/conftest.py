"""Test configuration: run everything on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without TPU hardware.

Env vars must be set before jax initializes its backends, hence this runs at
conftest import time (pytest imports conftest before test modules).
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)
