"""Test configuration: run everything on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without TPU hardware, and so the suite
is fast/deterministic.  Set UNICORE_TPU_TEST_ON_TPU=1 to run the suite
against the real chip instead (e.g. for Pallas kernel parity on hardware).

The dev image registers the TPU PJRT plugin from sitecustomize at
interpreter start, so JAX_PLATFORMS in the environment is not enough — we
must override the jax config before any backend is initialized.  conftest
import time is early enough (pytest imports conftest before test modules).
"""

import atexit
import os
import shutil
import tempfile

# hermetic kernel-autotune overlay: a developer machine's tune entries
# (~/.cache or an exported UNICORE_TPU_CACHE_DIR) must not steer
# dispatch (block choices) inside the suite — unconditional override
_tune_dir = tempfile.mkdtemp(prefix="unicore_tune_test_")
os.environ["UNICORE_TPU_CACHE_DIR"] = _tune_dir
atexit.register(shutil.rmtree, _tune_dir, ignore_errors=True)

if os.environ.get("UNICORE_TPU_TEST_ON_TPU", "") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def pytest_configure(config):
    # "slow": excluded from the tier-1 gate (pytest -m 'not slow') but
    # run by the CI workflow's full `pytest tests/` step — for tests
    # whose value is end-to-end coverage, not per-commit latency (e.g.
    # the Pass-3 CLI round-trip, which AOT-compiles the train step in
    # three subprocesses)
    config.addinivalue_line(
        "markers", "slow: heavy end-to-end test, excluded from tier-1"
    )
