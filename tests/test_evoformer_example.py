"""Full-Evoformer example plugin e2e: MSA + pair co-refinement through
the CLI on synthetic covariation data — the complete Uni-Fold Evoformer
workload (both halves), which examples/pair's pair-only stack doesn't
cover."""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("evodata"))
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "examples", "evoformer", "example_data",
                      "make_data.py"),
         "-o", data_dir, "--n-res", "12", "--n-seqs", "6", "--bins", "8",
         "--train", "48", "--valid", "8"],
        capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return data_dir


def test_evoformer_cli_trains_and_loss_decreases(corpus, tmp_path):
    save_dir = str(tmp_path / "ckpt")
    cmd = [
        sys.executable, "-m", "unicore_tpu_cli.train", corpus,
        "--user-dir", os.path.join(REPO, "examples", "evoformer"),
        "--task", "evoformer", "--loss", "evoformer_mse",
        "--arch", "evoformer",
        "--evoformer-layers", "1", "--msa-embed-dim", "16",
        "--pair-embed-dim", "16", "--msa-attention-heads", "2",
        "--pair-attention-heads", "2", "--opm-hidden-dim", "4",
        "--batch-size", "8", "--optimizer", "adam", "--lr", "3e-3",
        "--lr-scheduler", "fixed", "--max-update", "16",
        "--log-interval", "4", "--log-format", "simple",
        "--save-dir", save_dir,
        "--required-batch-size-multiple", "1", "--num-workers", "0", "--cpu",
    ]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=560, env=env, cwd=REPO
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "done training" in r.stdout
    assert "rmse" in r.stdout
    assert os.path.exists(os.path.join(save_dir, "checkpoint_last.pt"))

    losses = [float(m) for m in re.findall(r"\| loss ([\d.]+) \|", r.stdout)]
    assert len(losses) >= 2 and losses[-1] < losses[0], losses


@pytest.mark.slow  # ~49s of subprocess compile; tier-1 keeps the plain
# evoformer CLI run plus the structure-module unit tests
def test_evoformer_with_structure_module_trains(corpus, tmp_path):
    """North-star configs[2] end-to-end: Evoformer + STRUCTURE MODULE —
    distances come from the pairwise norms of the predicted C-alpha
    trace, so the MSE trains IPA and the backbone update through real
    3-D geometry."""
    save_dir = str(tmp_path / "ckpt_sm")
    cmd = [
        sys.executable, "-m", "unicore_tpu_cli.train", corpus,
        "--user-dir", os.path.join(REPO, "examples", "evoformer"),
        "--task", "evoformer", "--loss", "evoformer_mse",
        "--arch", "evoformer",
        "--evoformer-layers", "1", "--msa-embed-dim", "16",
        "--pair-embed-dim", "16", "--msa-attention-heads", "2",
        "--pair-attention-heads", "2", "--opm-hidden-dim", "4",
        "--structure-module", "True", "--structure-layers", "2",
        "--batch-size", "8", "--optimizer", "adam", "--lr", "3e-3",
        "--lr-scheduler", "fixed", "--max-update", "14",
        "--log-interval", "4", "--log-format", "simple",
        "--save-dir", save_dir,
        "--required-batch-size-multiple", "1", "--num-workers", "0", "--cpu",
    ]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=560, env=env, cwd=REPO
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "done training" in r.stdout
    losses = [float(m) for m in re.findall(r"\| loss ([\d.]+) \|", r.stdout)]
    assert len(losses) >= 2 and losses[-1] < losses[0], losses
    # a frozen model (zero-init saddle) logs gnorm 0 while batch noise
    # can still fake a "decreasing" loss — demand live gradients too
    gnorms = [float(m) for m in re.findall(r"gnorm[= ]([\d.e+-]+)", r.stdout)]
    assert gnorms and max(gnorms) > 0, gnorms
