"""Sequence-packed training (ISSUE 17 tentpole B).

Tiers:

- pure collator units: first-fit determinism, every-sample-in-exactly-
  one-bin coverage, capacity/segment caps, row metadata (contiguous
  1-based segments, per-segment position reset, pad fill);
- segment-causal mask units on ``_segment_bias`` + ``_attend``: no
  cross-segment attention, pad keys unattendable;
- model-level parity: packed rows produce BIT-EXACT per-token logits vs
  the padded run of the same logical samples (masked scores take the
  -1e30 fill whose softmax terms underflow to exact 0.0), loss/grads
  agree to reduction-order tolerance;
- rel_pos refusal: the global-offset bias cannot reset per segment;
- trainer integration: checkpoint save -> resume on packed batches is
  bit-exact vs the uninterrupted run.
"""

from argparse import Namespace

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu import metrics
from unicore_tpu.data.packing import PackedTokenDataset, pack_lengths
from unicore_tpu.modules.multihead_attention import (
    SelfMultiheadAttention,
    _segment_bias,
)

VOCAB, PAD, T = 37, 0, 32


# ---------------------------------------------------------------------
# collator units
# ---------------------------------------------------------------------

def test_pack_lengths_coverage_and_determinism():
    rng = np.random.RandomState(0)
    lengths = rng.randint(1, 20, size=64).tolist()
    bins = pack_lengths(lengths, 32)
    # every sample in exactly one bin
    flat = sorted(i for b in bins for i in b)
    assert flat == list(range(64))
    # capacity respected
    for b in bins:
        assert sum(lengths[i] for i in b) <= 32
    # pure function: identical layout on recompute
    assert pack_lengths(lengths, 32) == bins
    # packing actually packs (fewer rows than samples)
    assert len(bins) < 64


def test_pack_lengths_overlong_and_segment_cap():
    bins = pack_lengths([50, 3, 3, 3], 16, max_segments=2)
    assert bins[0] == [0]            # overlong sample isolated
    for b in bins:
        assert len(b) <= 2
    assert sorted(i for b in bins for i in b) == [0, 1, 2, 3]


def test_packed_dataset_row_metadata():
    lengths = [5, 4, 7, 20]
    inputs = [np.arange(1, n + 1, dtype=np.int64) for n in lengths]
    targets = [np.arange(2, n + 2, dtype=np.int64) for n in lengths]
    ds = PackedTokenDataset(inputs, targets, lengths, 16, PAD)
    seen = 0
    for r in range(len(ds)):
        row = ds[r]
        seg, pos, src = row["segment_ids"], row["positions"], row["src_tokens"]
        # segments 1-based, contiguous, pad tail is 0/-1/PAD
        n_real = int((seg != 0).sum())
        assert (seg[:n_real] != 0).all() and (seg[n_real:] == 0).all()
        assert (pos[n_real:] == -1).all() and (src[n_real:] == PAD).all()
        for s in range(1, seg.max() + 1):
            span = np.where(seg == s)[0]
            assert (np.diff(span) == 1).all()          # contiguous
            np.testing.assert_array_equal(             # positions reset
                pos[span], np.arange(len(span))
            )
            seen += 1
    assert seen == len(lengths)
    # collater produces the static-shape nested batch
    batch = ds.collater([ds[i] for i in range(len(ds))])
    assert batch["net_input"]["src_tokens"].shape == (len(ds), 16)
    assert batch["target"].shape == (len(ds), 16)


# ---------------------------------------------------------------------
# segment-causal mask units
# ---------------------------------------------------------------------

def test_segment_bias_blocks_cross_segment_and_pad():
    seg = jnp.asarray([[1, 1, 2, 2, 2, 0]])
    b = np.asarray(_segment_bias(seg, 6))[0, 0]        # [T, T]
    for qi in range(6):
        for ki in range(6):
            same = (seg[0, qi] == seg[0, ki]) and seg[0, ki] != 0
            if same:
                assert b[qi, ki] == 0.0
            else:
                assert b[qi, ki] <= -1e29, (qi, ki)


def test_attention_no_cross_segment_leakage():
    """Perturbing segment 1's tokens must not move segment 2's outputs."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 8, 16), jnp.float32)
    seg = jnp.asarray([[1, 1, 1, 2, 2, 2, 2, 0]])
    attn = SelfMultiheadAttention(16, 2, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), x)
    out = attn.apply(params, x, causal=True, segment_ids=seg)
    x2 = x.at[0, 1].set(100.0)                         # poke segment 1
    out2 = attn.apply(params, x2, causal=True, segment_ids=seg)
    np.testing.assert_array_equal(
        np.asarray(out)[0, 3:7], np.asarray(out2)[0, 3:7]
    )
    assert not np.array_equal(np.asarray(out)[0, :3], np.asarray(out2)[0, :3])


def test_decode_rejects_segment_ids():
    x = jnp.zeros((1, 4, 16), jnp.float32)
    attn = SelfMultiheadAttention(16, 2, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), x)
    with pytest.raises(NotImplementedError):
        attn.apply(params, x, decode=True,
                   segment_ids=jnp.ones((1, 4), jnp.int32),
                   mutable=["cache"])


# ---------------------------------------------------------------------
# model-level parity (packed == padded on the same logical samples)
# ---------------------------------------------------------------------

def _lm_model(rel_pos=False):
    # the shared module instance (same import path as test_decode /
    # test_serve) — a second instance would re-register the lm loss
    from examples.lm.model import TransformerLMModel

    return TransformerLMModel(
        vocab_size=VOCAB, padding_idx=PAD, decoder_layers=2,
        decoder_embed_dim=32, decoder_ffn_embed_dim=64,
        decoder_attention_heads=2, emb_dropout=0.0, dropout=0.0,
        attention_dropout=0.0, activation_dropout=0.0, max_seq_len=T,
        rel_pos=rel_pos, abs_pos=True,
    )


def _mixed_batches():
    """The same 3 logical samples, padded (one per row) and packed (one
    row, 10+7+12=29 <= 32)."""
    rng = np.random.RandomState(5)
    lens = [10, 7, 12]
    samples = [rng.randint(1, VOCAB, size=n).astype(np.int64) for n in lens]
    targets = [np.roll(s, -1) for s in samples]
    pad_src = np.full((3, T), PAD, np.int64)
    pad_tgt = np.full((3, T), PAD, np.int64)
    for i, (s, t) in enumerate(zip(samples, targets)):
        pad_src[i, : len(s)] = s
        pad_tgt[i, : len(t)] = t
    pk_src = np.full((1, T), PAD, np.int64)
    pk_tgt = np.full((1, T), PAD, np.int64)
    pk_seg = np.zeros((1, T), np.int32)
    pk_pos = np.full((1, T), -1, np.int32)
    off = 0
    for i, (s, t) in enumerate(zip(samples, targets), start=1):
        n = len(s)
        pk_src[0, off:off + n] = s
        pk_tgt[0, off:off + n] = t
        pk_seg[0, off:off + n] = i
        pk_pos[0, off:off + n] = np.arange(n)
        off += n
    return lens, (pad_src, pad_tgt), (pk_src, pk_tgt, pk_seg, pk_pos)


def test_packed_vs_padded_logits_bitexact():
    lens, (pad_src, _), (pk_src, _, pk_seg, pk_pos) = _mixed_batches()
    model = _lm_model()
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(pad_src))["params"]
    lp = np.asarray(model.apply({"params": params}, jnp.asarray(pad_src),
                                deterministic=True))
    lk = np.asarray(model.apply({"params": params}, jnp.asarray(pk_src),
                                deterministic=True,
                                segment_ids=jnp.asarray(pk_seg),
                                positions=jnp.asarray(pk_pos)))
    off = 0
    for i, n in enumerate(lens):
        np.testing.assert_array_equal(lp[i, :n], lk[0, off:off + n])
        off += n


def test_packed_vs_padded_loss_and_grad_parity():
    """Total loss and grads agree to reduction-order tolerance (the sums
    traverse tokens in a different order; the per-token terms are
    bit-identical per the logits test above)."""
    lens, (pad_src, pad_tgt), (pk_src, pk_tgt, pk_seg, pk_pos) = \
        _mixed_batches()
    model = _lm_model()
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(pad_src))["params"]

    def loss_fn(p, src, tgt, **kw):
        logits = model.apply({"params": p}, jnp.asarray(src),
                             deterministic=True, **kw)
        lprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        t = jnp.asarray(tgt)
        w = (t != PAD).astype(jnp.float32)
        safe = jnp.where(t != PAD, t, 0)
        nll = -jnp.take_along_axis(lprobs, safe[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * w), jnp.sum(w)

    (l_pad, n_pad), g_pad = jax.value_and_grad(loss_fn, has_aux=True)(
        params, pad_src, pad_tgt)
    (l_pk, n_pk), g_pk = jax.value_and_grad(loss_fn, has_aux=True)(
        params, pk_src, pk_tgt, segment_ids=jnp.asarray(pk_seg),
        positions=jnp.asarray(pk_pos))
    assert float(n_pad) == float(n_pk) == sum(lens)
    np.testing.assert_allclose(float(l_pk), float(l_pad), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_pad),
                    jax.tree_util.tree_leaves(g_pk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_rel_pos_refuses_packing():
    _, (pad_src, _), (pk_src, _, pk_seg, pk_pos) = _mixed_batches()
    model = _lm_model(rel_pos=True)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(pad_src))["params"]
    with pytest.raises(NotImplementedError):
        model.apply({"params": params}, jnp.asarray(pk_src),
                    segment_ids=jnp.asarray(pk_seg),
                    positions=jnp.asarray(pk_pos))


# ---------------------------------------------------------------------
# trainer integration: packed checkpoint resume
# ---------------------------------------------------------------------

def _packed_batch(rng, bsz=4):
    src = np.full((bsz, T), PAD, np.int64)
    tgt = np.full((bsz, T), PAD, np.int64)
    seg = np.zeros((bsz, T), np.int32)
    pos = np.full((bsz, T), -1, np.int32)
    for b in range(bsz):
        off = 0
        for s in range(1, 4):
            n = int(rng.randint(4, 10))
            if off + n > T:
                break
            toks = rng.randint(1, VOCAB, size=n).astype(np.int64)
            src[b, off:off + n] = toks
            tgt[b, off:off + n] = np.roll(toks, -1)
            seg[b, off:off + n] = s
            pos[b, off:off + n] = np.arange(n)
            off += n
    return {
        "net_input": {"src_tokens": src, "segment_ids": seg,
                      "positions": pos},
        "target": tgt,
    }


def _packed_trainer():
    from test_resilience import ToyLoss, ToyTask, make_args
    from unicore_tpu.models.unicore_model import BaseUnicoreModel
    from unicore_tpu.trainer import Trainer

    class PackedToyModel(BaseUnicoreModel):
        @nn.compact
        def __call__(self, src_tokens, deterministic=True, segment_ids=None,
                     positions=None, **kwargs):
            x = nn.Embed(VOCAB, 16, name="embed")(src_tokens)
            x = SelfMultiheadAttention(16, 2, dropout=0.0, name="attn")(
                x, causal=True, segment_ids=segment_ids,
                deterministic=deterministic,
            )
            return nn.Dense(VOCAB, name="out")(x)

    class PackedToyLoss(ToyLoss):
        def forward(self, model, params, sample, rng=None, is_training=True):
            logits = model.apply(
                {"params": params}, **sample["net_input"],
                deterministic=not is_training,
            )
            lprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            t = sample["target"]
            w = (t != PAD).astype(jnp.float32)
            safe = jnp.where(t != PAD, t, 0)
            nll = -jnp.take_along_axis(
                lprobs, safe[..., None], axis=-1)[..., 0]
            loss = jnp.sum(nll * w)
            n = jnp.sum(w)
            return loss, n, {"loss": loss, "sample_size": n}

    args = make_args()
    task = ToyTask(args)
    return Trainer(args, task, PackedToyModel(), PackedToyLoss(task))


def test_packed_checkpoint_resume_bit_exact(tmp_path):
    """Save mid-run on packed batches, resume, continue: params bit-equal
    to the uninterrupted run (the packed operands — segment_ids,
    positions — introduce no resume-variant state)."""
    rng = np.random.RandomState(7)
    batches = [_packed_batch(rng) for _ in range(4)]
    path = str(tmp_path / "ckpt_packed.pt")

    metrics.reset()
    trainer = _packed_trainer()
    with metrics.aggregate("train"):
        for b in batches[:2]:
            trainer.train_step([b])
        trainer.flush_stats()
    trainer.save_checkpoint(path, {"train_iterator": {"epoch": 1}})
    with metrics.aggregate("train"):
        for b in batches[2:]:
            trainer.train_step([b])
        trainer.flush_stats()
    want = jax.device_get(trainer.state["params"])

    metrics.reset()
    fresh = _packed_trainer()
    fresh.load_checkpoint(path)
    with metrics.aggregate("train"):
        fresh.init_state(batches[0])
        for b in batches[2:]:
            fresh.train_step([b])
        fresh.flush_stats()
    got = jax.device_get(fresh.state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(want),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
