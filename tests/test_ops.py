"""Parity tests for the functional ops, generalizing the reference's
``tests/test_softmax.py`` pattern: compare the framework op against an
independent eager composition (torch CPU here), across dims/dtypes, forward
and backward — including the 5-D triangle-attention broadcast shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from unicore_tpu import ops


def _torch_softmax(x, mask=None, bias=None):
    t = torch.from_numpy(np.asarray(x, dtype=np.float32))
    if mask is not None:
        t = t + torch.from_numpy(np.asarray(mask, dtype=np.float32))
    if bias is not None:
        t = t + torch.from_numpy(np.asarray(bias, dtype=np.float32))
    return torch.softmax(t, dim=-1).numpy()


@pytest.mark.parametrize("k", [64, 128, 256, 1024, 1536])
def test_softmax_dropout_forward(rng, k):
    x = rng.randn(2, 4, 16, k).astype(np.float32)
    mask = (rng.rand(2, 1, 1, k) > 0.5).astype(np.float32) * -10000.0
    bias = rng.randn(1, 4, 16, k).astype(np.float32)
    out = ops.softmax_dropout(
        jnp.asarray(x), 0.0, is_training=False, mask=jnp.asarray(mask), bias=jnp.asarray(bias)
    )
    ref = _torch_softmax(x, mask, bias)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


@pytest.mark.parametrize(
    "mask_shape,bias_shape",
    [
        # Uni-Fold Evoformer patterns (reference tests/test_softmax.py:81-170)
        ((2, 3, 1, 1, 32), (1, 1, 4, 16, 32)),
        ((2, 3, 4, 1, 32), (1, 3, 4, 16, 32)),
    ],
)
def test_softmax_dropout_triangle_broadcast(rng, mask_shape, bias_shape):
    x = rng.randn(2, 3, 4, 16, 32).astype(np.float32)
    mask = (rng.rand(*mask_shape) > 0.5).astype(np.float32) * -10000.0
    bias = rng.randn(*bias_shape).astype(np.float32)
    out = ops.softmax_dropout(
        jnp.asarray(x), 0.0, is_training=False, mask=jnp.asarray(mask), bias=jnp.asarray(bias)
    )
    np.testing.assert_allclose(np.asarray(out), _torch_softmax(x, mask, bias), atol=1e-5)


def test_softmax_dropout_grads_match_torch(rng):
    x = rng.randn(2, 4, 8, 64).astype(np.float32)
    bias = rng.randn(1, 4, 8, 64).astype(np.float32)

    def f(x_, b_):
        return jnp.sum(
            ops.softmax_dropout(x_, 0.0, is_training=False, bias=b_) ** 2
        )

    gx, gb = jax.grad(f, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(bias))

    tx = torch.from_numpy(x).requires_grad_(True)
    tb = torch.from_numpy(bias).requires_grad_(True)
    (torch.softmax(tx + tb, dim=-1) ** 2).sum().backward()
    np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), tb.grad.numpy(), atol=1e-4)


def test_softmax_dropout_training_mask_statistics(rng):
    x = jnp.asarray(rng.randn(4, 16, 256).astype(np.float32))
    key = jax.random.PRNGKey(0)
    out, sm = ops.softmax_dropout_reference(
        x, 0.5, rng=key, is_training=True, return_softmax=True
    )
    out = np.asarray(out)
    # dropped entries are exactly zero; survivors are scaled by 1/keep
    dropped = out == 0.0
    frac = dropped.mean()
    assert 0.4 < frac < 0.6
    survivors = ~dropped
    np.testing.assert_allclose(
        out[survivors], (np.asarray(sm) / 0.5)[survivors], rtol=1e-6
    )


@pytest.mark.parametrize("dim", [64, 100, 768])
def test_layer_norm_matches_torch(rng, dim):
    x = rng.randn(3, 7, dim).astype(np.float32)
    w = rng.randn(dim).astype(np.float32)
    b = rng.randn(dim).astype(np.float32)
    out = ops.layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    ref = torch.nn.functional.layer_norm(
        torch.from_numpy(x), (dim,), torch.from_numpy(w), torch.from_numpy(b)
    ).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_layer_norm_bf16_fp32_stats(rng):
    # bf16 input must use fp32 statistics: normalizing the bf16-quantized
    # input in fp32 (torch semantics) must agree with our bf16 path
    x = (rng.randn(4, 128) + 300.0).astype(np.float32)
    x_bf16 = jnp.asarray(x, dtype=jnp.bfloat16)
    out_bf16 = ops.layer_norm(x_bf16)
    ref = ops.layer_norm_reference(x_bf16.astype(jnp.float32))
    np.testing.assert_allclose(
        np.asarray(out_bf16, dtype=np.float32), np.asarray(ref), atol=0.1
    )


def test_fp32_to_bf16_sr_unbiased():
    # stochastic rounding must be unbiased: mean of many rounded copies
    # converges to the fp32 value, unlike truncation
    x = jnp.full((10000,), 1.0 + 1.0 / 512.0, dtype=jnp.float32)
    out = ops.fp32_to_bf16_sr(x, jax.random.PRNGKey(7))
    vals = np.asarray(out, dtype=np.float32)
    # bf16 neighbors of 1+1/512 are 1.0 and 1.0078125; both must occur
    assert set(np.unique(vals)) == {1.0, 1.0078125}
    np.testing.assert_allclose(vals.mean(), 1.0 + 1.0 / 512.0, rtol=3e-4)


def test_fp32_to_bf16_sr_exact_values_stable():
    # values already representable in bf16 never move
    x = jnp.asarray([0.0, 1.0, -2.5, 0.15625], dtype=jnp.float32)
    out = ops.fp32_to_bf16_sr(x, jax.random.PRNGKey(3))
    np.testing.assert_array_equal(
        np.asarray(out, dtype=np.float32), np.asarray(x)
    )


def test_l2_norm_tree(rng):
    tree = {
        "a": jnp.asarray(rng.randn(17, 5).astype(np.float32)),
        "b": {"c": jnp.asarray(rng.randn(3).astype(np.float32))},
    }
    flat = np.concatenate(
        [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(tree)]
    )
    np.testing.assert_allclose(
        float(ops.l2_norm(tree)), np.linalg.norm(flat), rtol=1e-6
    )


def test_uint8_dropout_statistics():
    """ops.dropout draws uint8 keep bits: the keep rate must match the
    QUANTIZED probability q/256 and survivors must be scaled by exactly
    256/q, so E[dropout(x)] == x holds precisely."""
    x = jnp.ones((512, 512), jnp.float32)
    key = jax.random.PRNGKey(0)
    out = np.asarray(ops.dropout(x, 0.1, key))
    q = round(0.9 * 256)  # 230
    kept = (out > 0).mean()
    assert abs(kept - q / 256.0) < 0.01, kept
    # exactly two values: 0 and the inverted-dropout scale
    vals = np.unique(out)
    np.testing.assert_allclose(
        vals, [0.0, 256.0 / q], rtol=1e-6
    )
    assert abs(out.mean() - 1.0) < 0.02
    # edge rates: identity below the quantization floor, full drop at ~1
    np.testing.assert_array_equal(
        np.asarray(ops.dropout(x, 0.0, key)), np.asarray(x)
    )
    assert np.asarray(ops.dropout(x, 0.999, key)).sum() == 0.0
    # deterministic per rng key (the backward replays the same mask)
    np.testing.assert_array_equal(out, np.asarray(ops.dropout(x, 0.1, key)))
    other = np.asarray(ops.dropout(x, 0.1, jax.random.PRNGKey(1)))
    assert (out != other).any()
