"""Kernel-autotuning subsystem (ops/tuning): cache round-trip +
environment-fingerprint invalidation, shape bucketing boundaries,
eager-crossover dispatch, tuned-config threading (probe keys, row
blocks, flash blocks), and deterministic tuner picks under interpret
mode with fixed fake timings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu import ops
from unicore_tpu.ops import tuning
from unicore_tpu.ops.tuning import TuneCache, bucket_key, candidates
from unicore_tpu.ops.tuning.tuner import tune_bucket, tune_workloads


@pytest.fixture
def tune_env(tmp_path, monkeypatch):
    """Isolated cache file + clean tuning state; restores state after."""
    path = str(tmp_path / "tune_cache.json")
    cache = TuneCache(paths=[path], fingerprint="fmtT|testdev|jaxT|libtpuT")
    tuning.reset(mode="cache")
    monkeypatch.setattr(tuning, "get_cache", lambda: cache)
    yield cache
    tuning.reset(mode="cache")


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------


def test_cache_roundtrip(tmp_path):
    path = str(tmp_path / "c.json")
    c1 = TuneCache(paths=[path], fingerprint="fp1")
    c1.record("softmax_dropout|k1", {"q_blk": 64}, micros_us={"eager": 10.0})
    c1.record("softmax_dropout|k2", "eager")
    c2 = TuneCache(paths=[path], fingerprint="fp1")
    assert c2.lookup("softmax_dropout|k1") == {"q_blk": 64}
    assert c2.lookup("softmax_dropout|k2") == "eager"
    assert c2.get("softmax_dropout|k1")["micros_us"] == {"eager": 10.0}
    assert c2.lookup("softmax_dropout|missing") is None


def test_cache_version_key_invalidation(tmp_path):
    """An entry tuned under another environment fingerprint (device
    kind / jax / libtpu change) must read as a miss — stale configs
    self-invalidate to the heuristic path."""
    path = str(tmp_path / "c.json")
    TuneCache(paths=[path], fingerprint="v5e|jax0.4").record(
        "flash|k", {"block_q": 512, "block_k": 2048}
    )
    stale = TuneCache(paths=[path], fingerprint="v4|jax0.5")
    assert stale.lookup("flash|k") is None
    # and the original fingerprint still sees it
    assert TuneCache(paths=[path], fingerprint="v5e|jax0.4").lookup(
        "flash|k"
    ) == {"block_q": 512, "block_k": 2048}


def test_cache_dry_entries_never_steer_dispatch(tmp_path):
    """Fake-timing (dry-run) entries are reused by the tuner's
    warm-cache check but must read as misses for dispatch decisions."""
    path = str(tmp_path / "c.json")
    c = TuneCache(paths=[path], fingerprint="fp")
    c.record("k", {"q_blk": 8}, source="dry")
    assert c.lookup("k") is None
    assert c.get("k")["winner"] == {"q_blk": 8}
    c.record("k", {"q_blk": 8}, source="timed")
    assert c.lookup("k") == {"q_blk": 8}


def test_cache_overlay_wins_and_corrupt_reads_empty(tmp_path):
    repo = tmp_path / "repo.json"
    overlay = tmp_path / "overlay.json"
    TuneCache(paths=[str(repo)], fingerprint="fp").record("k", "eager")
    c = TuneCache(paths=[str(repo), str(overlay)], fingerprint="fp")
    assert c.lookup("k") == "eager"
    c.record("k", {"q_blk": 8})
    c2 = TuneCache(paths=[str(repo), str(overlay)], fingerprint="fp")
    assert c2.lookup("k") == {"q_blk": 8}
    # the overlay write must not have clobbered the repo layer
    assert TuneCache(paths=[str(repo)], fingerprint="fp").lookup("k") == "eager"
    # corrupt file -> empty cache, no raise
    overlay.write_text("{not json")
    c3 = TuneCache(paths=[str(overlay)], fingerprint="fp")
    assert c3.lookup("k") is None


# ---------------------------------------------------------------------------
# bucketing
# ---------------------------------------------------------------------------


def test_pow2_bucket_boundaries():
    assert tuning.pow2_bucket(1) == 1
    assert tuning.pow2_bucket(128) == 128
    assert tuning.pow2_bucket(129) == 256
    assert tuning.pow2_bucket(384) == 512
    assert tuning.pow2_bucket(512) == 512
    assert tuning.pow2_bucket(513) == 1024


def test_sd_bucket_rounds_rows_keeps_patterns():
    wl_a = tuning.sd_workload((32, 12, 512, 512), "bfloat16",
                              bias=((1, 12, 512, 512), "bfloat16"))
    wl_b = tuning.sd_workload((8, 4, 400, 512), "bfloat16",
                              bias=((1, 4, 400, 512), "bfloat16"))
    # lead dims and exact row counts wash out (400 -> 512)
    assert candidates.OPS["softmax_dropout"].bucket(wl_a) == \
        candidates.OPS["softmax_dropout"].bucket(wl_b)
    # a different broadcast pattern is a different bucket
    wl_c = tuning.sd_workload((32, 12, 512, 512), "bfloat16",
                              bias=((1, 1, 512, 512), "bfloat16"))
    assert candidates.OPS["softmax_dropout"].bucket(wl_a) != \
        candidates.OPS["softmax_dropout"].bucket(wl_c)


def test_flash_bucket_exact_head_dim_and_bias_class():
    mk = lambda d, bias: tuning.flash_workload(
        (4, 512, 8, d), 512, "bfloat16", bias=bias, dropout_on=True,
    )
    b = candidates.OPS["flash_attention"].bucket
    # head-dim is exact: 64 vs 80 are different buckets
    assert b(mk(64, None)) != b(mk(80, None))
    # bias-head broadcastness does NOT split the bucket (see
    # candidates._flash_bias_class) but q-broadcastness does
    assert b(mk(64, ((1, 8, 512, 512), "bfloat16"))) == \
        b(mk(64, ((1, 1, 512, 512), "bfloat16")))
    assert b(mk(64, ((1, 8, 512, 512), "bfloat16"))) != \
        b(mk(64, ((1, 8, 1, 512), "bfloat16")))
    # batch washes out
    assert b(mk(64, None)) == b(tuning.flash_workload(
        (64, 512, 8, 64), 512, "bfloat16", dropout_on=True,
    ))


def test_tuned_config_validation():
    assert tuning.tuned_flash_blocks(512, 512,
                                     {"block_q": 256, "block_k": 512}) \
        == (256, 512)
    # non-dividing / oversized / misaligned / malformed -> heuristic
    assert tuning.tuned_flash_blocks(384, 512,
                                     {"block_q": 256, "block_k": 512}) is None
    assert tuning.tuned_flash_blocks(512, 512,
                                     {"block_q": 1024, "block_k": 512}) is None
    assert tuning.tuned_flash_blocks(512, 512,
                                     {"block_q": 12, "block_k": 512}) is None
    assert tuning.tuned_flash_blocks(512, 512, {"block_q": 256}) is None
    assert tuning.tuned_flash_blocks(512, 512, "eager") is None
    assert tuning.tuned_q_blk(128, {"q_blk": 32}) == 32
    assert tuning.tuned_q_blk(128, {"q_blk": 48}) is None
    assert tuning.tuned_q_blk(128, {"q_blk": 256}) is None
    assert tuning.tuned_q_blk(128, None) is None


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _evo_arrays(rng):
    x = jnp.asarray(rng.randn(1, 16, 4, 128, 128).astype(np.float32))
    mask = jnp.asarray(
        (rng.rand(1, 16, 1, 1, 128) > 0.1).astype(np.float32) * -1e9
    )
    bias = jnp.asarray(rng.randn(1, 1, 4, 128, 128).astype(np.float32))
    return x, mask, bias


def test_eager_crossover_dispatch(tune_env, monkeypatch, rng):
    """A cached "eager" verdict must route AUTO dispatch around the
    kernel entirely — the kernel implementation is never consulted."""
    import importlib

    sd_mod = importlib.import_module("unicore_tpu.ops.softmax_dropout")

    x, mask, bias = _evo_arrays(rng)
    wl = tuning.sd_workload(
        x.shape, x.dtype.name,
        mask=(mask.shape, mask.dtype.name), bias=(bias.shape, bias.dtype.name),
        dropout_on=False,
    )
    key = bucket_key(candidates.OPS["softmax_dropout"].bucket(wl))
    tune_env.record(key, "eager")

    monkeypatch.setattr(sd_mod, "use_pallas", lambda: True)

    def boom(*a, **k):
        raise AssertionError("kernel path taken despite eager verdict")

    import unicore_tpu.ops.pallas.softmax_dropout as pl_sd

    monkeypatch.setattr(pl_sd, "softmax_dropout", boom)
    out = ops.softmax_dropout(x, 0.0, is_training=False, mask=mask, bias=bias)
    ref = ops.softmax_dropout_reference(
        x, 0.0, is_training=False, mask=mask, bias=bias
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_tuned_q_blk_dispatch(tune_env, monkeypatch, rng):
    """A cached row-block config must reach the Pallas impl as q_blk."""
    import importlib

    sd_mod = importlib.import_module("unicore_tpu.ops.softmax_dropout")

    x, mask, bias = _evo_arrays(rng)
    wl = tuning.sd_workload(
        x.shape, x.dtype.name,
        mask=(mask.shape, mask.dtype.name), bias=(bias.shape, bias.dtype.name),
        dropout_on=False,
    )
    key = bucket_key(candidates.OPS["softmax_dropout"].bucket(wl))
    tune_env.record(key, {"q_blk": 32})

    monkeypatch.setattr(sd_mod, "use_pallas", lambda: True)
    import unicore_tpu.ops.pallas.softmax_dropout as pl_sd

    seen = {}
    real = pl_sd.softmax_dropout

    def spy(*a, **kw):
        seen["q_blk"] = kw.get("q_blk")
        return real(*a, **kw)

    monkeypatch.setattr(pl_sd, "softmax_dropout", spy)
    out = ops.softmax_dropout(x, 0.0, is_training=False, mask=mask, bias=bias)
    assert seen["q_blk"] == 32
    ref = ops.softmax_dropout_reference(
        x, 0.0, is_training=False, mask=mask, bias=bias
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_inapplicable_tuned_q_blk_falls_to_heuristic_path(tune_env,
                                                          monkeypatch, rng):
    """A cached config whose q_blk doesn't validate for the actual row
    count was never measured as-lowered: dispatch must fall through to
    the heuristic path (which gates this small-work shape to eager),
    not trust the verdict with substitute blocks."""
    import importlib

    sd_mod = importlib.import_module("unicore_tpu.ops.softmax_dropout")

    x = jnp.asarray(rng.randn(1, 4, 96, 128).astype(np.float32))
    wl = tuning.sd_workload(x.shape, x.dtype.name, dropout_on=False)
    key = bucket_key(candidates.OPS["softmax_dropout"].bucket(wl))
    tune_env.record(key, {"q_blk": 128})  # 128 > 96 rows: inapplicable

    monkeypatch.setattr(sd_mod, "use_pallas", lambda: True)
    import unicore_tpu.ops.pallas.softmax_dropout as pl_sd

    def boom(*a, **k):
        raise AssertionError("kernel lowered on an unmeasured config")

    monkeypatch.setattr(pl_sd, "softmax_dropout", boom)
    out = ops.softmax_dropout(x, 0.0, is_training=False)
    ref = ops.softmax_dropout_reference(x, 0.0, is_training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_real_tune_retimes_dry_entries(tune_env):
    """A dry (fake-timing) entry never serves dispatch, so a REAL tune
    run must re-time the bucket instead of 'reusing' it."""
    wl = tuning.ln_workload(8, 128, "float32")
    spec = candidates.OPS["layer_norm"]
    s1, key, e1 = tune_bucket(spec, wl, tune_env,
                              timer=lambda k, c: 1.0)
    assert s1 == "timed" and e1["source"] == "dry"
    # dry rerun reuses (the CI zero-re-timings check)...
    s2, _, _ = tune_bucket(spec, wl, tune_env, timer=lambda k, c: 1.0)
    assert s2 == "reused"
    # ...but a real (device-timed) run does not
    s3, _, e3 = tune_bucket(spec, wl, tune_env)
    assert s3 == "timed" and e3["source"] == "timed"


def test_pallas_sd_explicit_q_blk_matches_reference(rng):
    """The q_blk override changes tiling only, never numerics (dropout
    off: the grid-derived seed layout differs by block size, which is
    why probe keys and fwd/bwd must share one q_blk)."""
    from unicore_tpu.ops.pallas import softmax_dropout as pl_sd

    x = jnp.asarray(rng.randn(2, 4, 64, 128).astype(np.float32))
    ref = ops.softmax_dropout_reference(x, 0.0, is_training=False)
    for blk in (8, 16, 64, None, 999):  # 999 is invalid -> heuristic
        out = pl_sd.softmax_dropout(x, 0.0, is_training=False, q_blk=blk)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)


def test_flash_picked_blocks_honor_cache(tune_env):
    from unicore_tpu.ops.pallas import flash_attention as fa

    wl = tuning.flash_workload((1, 256, 1, 64), 256, "float32")
    key = bucket_key(candidates.OPS["flash_attention"].bucket(wl))
    tune_env.record(key, {"block_q": 128, "block_k": 128})
    got = fa.picked_blocks(256, 256, dtype=jnp.float32, d=64)
    assert got == (128, 128)
    # same shapes WITHOUT the tuner info kwargs -> heuristic (no crash)
    assert fa.picked_blocks(256, 256) == fa._pick_blocks(256, 256, 0)


def test_flash_tuned_blocks_parity(tune_env, rng):
    """A tuned block pair must lower (interpret mode here) and produce
    the same numerics as the reference — fwd and bwd trace the same
    memoized decision, so grads stay consistent."""
    from unicore_tpu.ops.pallas.flash_attention import flash_attention

    wl = tuning.flash_workload((2, 256, 2, 64), 256, "float32")
    key = bucket_key(candidates.OPS["flash_attention"].bucket(wl))
    tune_env.record(key, {"block_q": 128, "block_k": 128})

    q = jnp.asarray(rng.randn(2, 256, 2, 64).astype(np.float32))

    def fl(q_):
        return jnp.sum(flash_attention(q_, q_, q_, is_training=False) ** 2)

    def ref(q_):
        qt = jnp.einsum("bqhd->bhqd", q_)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, qt) * (64 ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bqhd", p, qt) ** 2)

    o1, g1 = jax.value_and_grad(fl)(q)
    o2, g2 = jax.value_and_grad(ref)(q)
    np.testing.assert_allclose(float(o1), float(o2), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=2e-3)


def test_flash_decision_memoized_for_fwd_bwd_agreement(tune_env):
    """The first consult freezes the decision: a cache write between
    the forward and backward trace of one custom_vjp must not flip the
    block choice (dropout mask layouts are grid-dependent)."""
    from unicore_tpu.ops.pallas import flash_attention as fa

    wl = tuning.flash_workload((1, 256, 1, 64), 256, "float32")
    key = bucket_key(candidates.OPS["flash_attention"].bucket(wl))
    heur = fa.picked_blocks(256, 256, dtype=jnp.float32, d=64)
    tune_env.record(key, {"block_q": 128, "block_k": 128})
    # memoized at first consult -> still the heuristic pair
    assert fa.picked_blocks(256, 256, dtype=jnp.float32, d=64) == heur
    tuning.reset_memo()
    assert fa.picked_blocks(256, 256, dtype=jnp.float32, d=64) == (128, 128)


def test_flash_probe_key_threads_tuned_blocks(tune_env):
    """probe_ok must key on the blocks production will lower: a changed
    tune-cache entry yields a DIFFERENT probe key (no stale verdicts)."""
    from unicore_tpu.ops import backend
    from unicore_tpu.ops.pallas import flash_attention as fa

    probed = []

    def spy(key, build):
        probed.append(key)
        return True

    orig = backend.kernel_probe_ok
    backend.kernel_probe_ok = spy
    try:
        fa.probe_ok(jnp.float32, 256, 256, 64, None, None, False, False,
                    False)
        wl = tuning.flash_workload((1, 256, 1, 64), 256, "float32")
        key = bucket_key(candidates.OPS["flash_attention"].bucket(wl))
        tune_env.record(key, {"block_q": 128, "block_k": 128})
        tuning.reset_memo()
        fa.probe_ok(jnp.float32, 256, 256, 64, None, None, False, False,
                    False)
    finally:
        backend.kernel_probe_ok = orig
    assert len(probed) == 2 and probed[0] != probed[1]
    assert probed[0][-2:] == fa._pick_blocks(256, 256, 0)
    assert probed[1][-2:] == (128, 128)


def test_off_mode_ignores_cache(tune_env):
    wl = tuning.sd_workload((2, 64, 128), "float32", dropout_on=False)
    key = bucket_key(candidates.OPS["softmax_dropout"].bucket(wl))
    tune_env.record(key, "eager")
    tuning.set_autotune_mode("off")
    assert tuning.softmax_dropout_decision(
        (2, 64, 128), "float32", dropout_on=False
    ) is None
    tuning.set_autotune_mode("cache")
    tuning.reset_memo()
    assert tuning.softmax_dropout_decision(
        (2, 64, 128), "float32", dropout_on=False
    ) == "eager"


def test_heuristic_crossover_gate(rng):
    """Satellite: the no-cache default must not lower a kernel slower
    than eager for small-row/batched-bias shapes (the BENCH_r05
    evoformer case) while keeping the shapes where the kernel wins."""
    from unicore_tpu.ops.softmax_dropout import _heuristic_kernel_win

    # evoformer: 5-D, batched mask, 128-row/128-k -> tiny per-program work
    xe = jnp.zeros((1, 128, 4, 128, 128), jnp.bfloat16)
    me = jnp.zeros((1, 128, 1, 1, 128), jnp.bfloat16)
    be = jnp.zeros((1, 1, 4, 128, 128), jnp.bfloat16)
    assert not _heuristic_kernel_win(xe, me, be)
    # BERT shape: wins (BENCH_r05 1.134x)
    xb = jnp.zeros((32, 12, 512, 512), jnp.bfloat16)
    bb = jnp.zeros((1, 12, 512, 512), jnp.bfloat16)
    assert _heuristic_kernel_win(xb, None, bb)
    # long-k rows: wins (BENCH_r05 1.108x)
    xk = jnp.zeros((4, 8, 1024, 2048), jnp.bfloat16)
    bk = jnp.zeros((1, 8, 1024, 2048), jnp.bfloat16)
    assert _heuristic_kernel_win(xk, None, bk)


# ---------------------------------------------------------------------------
# tuner (interpret mode, fixed fake timings)
# ---------------------------------------------------------------------------


def _fixed_timer(timings):
    def timer(key, config):
        return timings[candidates.describe_config(config)]

    return timer


def test_tuner_picks_fastest_kernel_config(tune_env):
    wl = tuning.sd_workload((1, 64, 128), "float32",
                            dropout_on=False)
    spec = candidates.OPS["softmax_dropout"]
    names = [candidates.describe_config(c) for c in spec.candidates(wl)]
    timings = {n: 100.0 for n in names}
    timings["eager"] = 50.0
    timings["q_blk=16"] = 10.0  # clear winner, beats eager x margin
    status, key, entry = tune_bucket(
        spec, wl, tune_env, timer=_fixed_timer(timings)
    )
    assert status == "timed"
    assert entry["winner"] == {"q_blk": 16}
    assert entry["source"] == "dry"
    # identical timings -> identical pick (determinism), and the entry
    # is REUSED: zero re-timings on the second invocation
    status2, _, entry2 = tune_bucket(
        spec, wl, tune_env, timer=_fixed_timer(timings)
    )
    assert status2 == "reused" and entry2["winner"] == {"q_blk": 16}


def test_tuner_eager_crossover_and_margin(tune_env):
    """Eager wins the bucket when no kernel config beats it by the
    noise margin — a tie routed to the kernel is downside-only."""
    wl = tuning.sd_workload((1, 64, 128), "float32", dropout_on=False)
    spec = candidates.OPS["softmax_dropout"]
    names = [candidates.describe_config(c) for c in spec.candidates(wl)]
    timings = {n: 100.0 for n in names}
    timings["eager"] = 100.0  # every kernel config merely ties
    _, _, entry = tune_bucket(spec, wl, tune_env,
                              timer=_fixed_timer(timings), force=True)
    assert entry["winner"] == "eager"


def test_tune_workloads_dry_run_deterministic(tmp_path):
    """The CI plumbing check: dry-run over presets is deterministic and
    the second run reuses every entry."""
    cache = TuneCache(paths=[str(tmp_path / "c.json")], fingerprint="fpX")
    wls = [
        tuning.sd_workload((1, 4, 64, 128), "float32", dropout_on=False),
        tuning.ln_workload(64, 128, "float32"),
    ]
    r1 = tune_workloads(wls, cache, dry_run=True)
    assert r1["timed"] == 2 and r1["reused"] == 0
    winners1 = {k: v["winner"] for k, v in r1["entries"].items()}
    # layer_norm has exactly one candidate: eager by walkover
    assert winners1[[k for k in winners1 if k.startswith("layer_norm")][0]] \
        == "eager"
    cache2 = TuneCache(paths=[str(tmp_path / "c.json")], fingerprint="fpX")
    r2 = tune_workloads(wls, cache2, dry_run=True)
    assert r2["timed"] == 0 and r2["reused"] == 2
    assert {k: v["winner"] for k, v in r2["entries"].items()} == winners1


def test_sd_shrink_preserves_patterns_and_bucket():
    """The dry-run shrink must not flip broadcast patterns: shrunk and
    full workloads lower the same BlockSpec variants and record under
    the same bucket key."""
    for name in ("sd_evoformer", "sd_bert", "sd_k2048"):
        wl = tuning.PRESETS[name]
        spec = candidates.OPS[wl["op"]]
        assert spec.bucket(spec.shrink(wl)) == spec.bucket(wl), name


def test_cli_dry_run_defaults_away_from_overlay(tmp_path, monkeypatch):
    """unicore_tune tune --dry-run without --cache must not write fake
    timings into the user overlay."""
    from unicore_tpu.ops.tuning import cache as cache_mod
    from unicore_tpu.ops.tuning.cli import main

    overlay_dir = tmp_path / "overlay"
    monkeypatch.setenv("UNICORE_TPU_CACHE_DIR", str(overlay_dir))
    assert main(["tune", "--dry-run", "--workloads", "layer_norm_bert",
                 "-q"]) == 0
    assert not (overlay_dir / "kernel_tune_cache.json").exists()
    assert cache_mod.overlay_cache_path().startswith(str(overlay_dir))


def test_lookup_only_consults_never_tune(tune_env, monkeypatch):
    """picked_blocks-style consults (allow_tune unset) must not trigger
    tune-mode timing — their synthesized workloads carry degenerate
    batch/head extents."""
    tuning.set_autotune_mode("tune")
    monkeypatch.setattr(tuning, "_can_tune_here", lambda: True)
    called = []

    def boom(*a, **k):
        called.append(a)
        raise AssertionError("tuned from a lookup-only consult")

    import unicore_tpu.ops.tuning.tuner as tuner_mod

    monkeypatch.setattr(tuner_mod, "tune_bucket", boom)
    assert tuning.flash_decision((1, 256, 1, 64), 256, "float32") is None
    assert not called


def test_forced_config_context(tune_env):
    with tuning.forced_config("flash_attention",
                              {"block_q": 128, "block_k": 128}):
        d = tuning.flash_decision((1, 256, 1, 64), 256, "float32")
        assert d == {"block_q": 128, "block_k": 128}
    assert tuning.flash_decision((1, 256, 1, 64), 256, "float32") is None


# ---------------------------------------------------------------------------
# fused chunked linear+cross-entropy (ISSUE 10)
# ---------------------------------------------------------------------------


def test_ce_bucket_rounds_rows_vocab_keeps_hidden():
    b = candidates.OPS["fused_cross_entropy"].bucket
    # rows/vocab pow2-bucket (8192 covers 8000), hidden stays exact
    assert b(tuning.ce_workload(8000, 768, 30528, "bfloat16")) == \
        b(tuning.ce_workload(8192, 768, 32768, "bfloat16"))
    assert b(tuning.ce_workload(8192, 768, 30528, "bfloat16")) != \
        b(tuning.ce_workload(8192, 1024, 30528, "bfloat16"))
    assert b(tuning.ce_workload(8192, 768, 30528, "bfloat16", tied=False)) \
        != b(tuning.ce_workload(8192, 768, 30528, "bfloat16", tied=True))


def test_ce_candidates_eager_always_chunks_bounded():
    wl = tuning.ce_workload(8192, 768, 30528, "bfloat16")
    cands = candidates.OPS["fused_cross_entropy"].candidates(wl)
    assert cands[0] == "eager"
    chunks = [c["chunk"] for c in cands[1:]]
    assert chunks and all(1 <= c <= wl["rows"] for c in chunks)
    assert len(set(chunks)) == len(chunks)
    # the op's own heuristic pick is always in the running
    from unicore_tpu.ops.fused_cross_entropy import pick_chunk

    assert pick_chunk(wl["rows"], wl["vocab"]) in chunks


def test_tuned_ce_chunk_validation():
    assert tuning.tuned_ce_chunk(1024, {"chunk": 256}) == 256
    assert tuning.tuned_ce_chunk(128, {"chunk": 256}) == 128  # clamped
    assert tuning.tuned_ce_chunk(1024, {"chunk": 0}) is None
    assert tuning.tuned_ce_chunk(1024, "eager") is None
    assert tuning.tuned_ce_chunk(1024, None) is None
    assert tuning.tuned_ce_chunk(1024, {"q_blk": 64}) is None


def test_ce_cached_verdicts_steer_dispatch(tune_env):
    """A cached {"chunk": n} reaches the op's chunk resolution; a cached
    "eager" retires the fused path for the bucket."""
    from unicore_tpu.ops import fused_cross_entropy as fce

    rows, hidden, vocab = 4096, 64, 512
    wl = tuning.ce_workload(rows, hidden, vocab, "float32")
    key = bucket_key(candidates.OPS["fused_cross_entropy"].bucket(wl))

    tune_env.record(key, {"chunk": 96})
    tuning.reset_memo()
    assert fce._resolve_chunk(rows, hidden, vocab, "float32", True,
                              True) == 96
    tune_env.record(key, "eager")
    tuning.reset_memo()
    assert fce._resolve_chunk(rows, hidden, vocab, "float32", True,
                              True) is None
    # a miss past FUSE_MIN_BYTES falls to the byte heuristic (vocab
    # 8192 -> chunk 1024 < rows, a genuinely chunkable bucket)
    other = tuning.ce_workload(rows, hidden, 8192, "float32")
    assert bucket_key(
        candidates.OPS["fused_cross_entropy"].bucket(other)) != key
    assert fce._resolve_chunk(rows, hidden, 8192, "float32", True, True) \
        == fce.pick_chunk(rows, 8192)


def test_ce_runner_builds_fused_and_eager(tune_env):
    """Both candidate runners AOT-compile (the dry-run path CI walks)."""
    wl = candidates.OPS["fused_cross_entropy"].shrink(
        tuning.PRESETS["fused_ce_bert"]
    )
    for config in ("eager", {"chunk": 64}):
        fn = candidates.OPS["fused_cross_entropy"].build_runner(wl, config)
        out = fn()
        assert all(np.all(np.isfinite(np.asarray(x))) for x in out)


def test_evoformer_static_verdict_out_of_the_box(tune_env):
    """The BENCH_r05 evoformer bucket (~0.99x kernel-vs-eager) carries a
    committed "eager" verdict: with an EMPTY cache, dispatch must route
    to eager for both dropout states — and a measured cache entry must
    still override the static verdict."""
    mask = ((1, 128, 1, 1, 128), "bfloat16")
    bias = ((1, 1, 4, 128, 128), "bfloat16")
    for dropout_on in (True, False):
        assert tuning.softmax_dropout_decision(
            (1, 128, 4, 128, 128), "bfloat16", mask=mask, bias=bias,
            dropout_on=dropout_on,
        ) == "eager"
    # a different (winning) bucket stays on the heuristics
    assert tuning.softmax_dropout_decision(
        (32, 12, 512, 512), "bfloat16",
        bias=((1, 12, 512, 512), "bfloat16"), dropout_on=True,
    ) is None
    wl = tuning.sd_workload(
        (1, 128, 4, 128, 128), "bfloat16", mask=mask, bias=bias,
        dropout_on=True,
    )
    key = bucket_key(candidates.OPS["softmax_dropout"].bucket(wl))
    assert key in tuning.STATIC_VERDICTS
    tune_env.record(key, {"q_blk": 128})
    tuning.reset_memo()
    assert tuning.softmax_dropout_decision(
        (1, 128, 4, 128, 128), "bfloat16", mask=mask, bias=bias,
        dropout_on=True,
    ) == {"q_blk": 128}


def test_cli_dry_run_roundtrip(tmp_path, capsys):
    """End-to-end CLI: tune --dry-run twice against one cache file; the
    second report shows zero re-timings; `cache` mode reads it back."""
    import json

    from unicore_tpu.ops.tuning.cli import main

    cache = str(tmp_path / "cli_cache.json")
    rep1, rep2 = str(tmp_path / "r1.json"), str(tmp_path / "r2.json")
    args = ["tune", "--dry-run", "--cache", cache,
            "--workloads", "sd_evoformer,layer_norm_bert", "-q"]
    assert main(args + ["--json", rep1]) == 0
    assert main(args + ["--json", rep2]) == 0
    r1, r2 = json.load(open(rep1)), json.load(open(rep2))
    assert r1["timed"] == 2 and r1["reused"] == 0
    assert r2["timed"] == 0 and r2["reused"] == 2
    assert {k: v["winner"] for k, v in r1["entries"].items()} == \
        {k: v["winner"] for k, v in r2["entries"].items()}
    assert main(["cache", "--cache", cache, "-q"]) == 0


# ---------------------------------------------------------------------
# optim_sr_cast (ISSUE 15: bf16-moment SR re-quantization)
# ---------------------------------------------------------------------

def test_sr_cast_bucket_and_candidates():
    b = candidates.OPS["optim_sr_cast"].bucket
    # one entry covers a pow2 family of leaf sizes
    assert b(tuning.sr_cast_workload(500_000)) == \
        b(tuning.sr_cast_workload(524_288))
    assert b(tuning.sr_cast_workload(524_288)) != \
        b(tuning.sr_cast_workload(1_048_576))
    wl = tuning.sr_cast_workload(768 * 768)
    cands = candidates.OPS["optim_sr_cast"].candidates(wl)
    assert cands[0] == "eager" and {"impl": "pallas"} in cands
    # dry-run shrink keeps the workload well-formed and small
    small = candidates.OPS["optim_sr_cast"].shrink(wl)
    assert small["n"] <= 4096 and small["op"] == "optim_sr_cast"
    assert "optim_sr_cast_moments" in tuning.PRESETS


def test_sr_cast_runner_builds_both_candidates(tune_env):
    """Both candidate runners AOT-compile and preserve value brackets:
    every output sits within one bf16 ulp of the input (the two impls
    draw different random streams, so PARITY here is the rounding
    contract, not bitwise equality)."""
    wl = candidates.OPS["optim_sr_cast"].shrink(
        tuning.PRESETS["optim_sr_cast_moments"]
    )
    for config in ("eager", {"impl": "pallas"}):
        fn = candidates.OPS["optim_sr_cast"].build_runner(wl, config)
        out = np.asarray(fn(), np.float64)
        assert out.size == wl["n"] or out.size >= wl["n"]
        assert np.all(np.isfinite(out))


def test_sr_cast_cached_verdict_steers_dispatch(tune_env, rng):
    """A cached "eager" verdict must route ops.fp32_to_bf16_sr to the
    threefry reference even when the pallas backend is forced."""
    import jax

    from unicore_tpu.ops import backend as ops_backend
    from unicore_tpu.ops.rounding import (
        fp32_to_bf16_sr,
        fp32_to_bf16_sr_reference,
    )

    x = jnp.asarray(rng.randn(2048), jnp.float32)
    key = jax.random.PRNGKey(3)
    wl = tuning.sr_cast_workload(x.size)
    bucket = bucket_key(candidates.OPS["optim_sr_cast"].bucket(wl))
    tune_env.record(bucket, "eager")
    tuning.reset_memo()
    prev = ops_backend.get_kernel_backend()
    try:
        ops_backend.set_kernel_backend("pallas")
        got = fp32_to_bf16_sr(x, key)
    finally:
        ops_backend.set_kernel_backend(prev)
    np.testing.assert_array_equal(
        np.asarray(got, np.float32),
        np.asarray(fp32_to_bf16_sr_reference(x, key), np.float32),
    )


def test_sr_cast_decision_never_raises(tune_env):
    # with an empty cache every size falls through to the heuristics
    # (None); odd sizes must never raise out of the dispatch consult
    for n in (1, 7, 1023, 768 * 768, 10 ** 9):
        assert tuning.sr_cast_decision(n) is None
