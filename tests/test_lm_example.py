"""Causal-LM example plugin e2e: decoder stack, user-dir loss
registration, derived ppl metric — the ``TransformerDecoder`` consumer
the BERT example doesn't exercise."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("lmdata"))
    sys.path.insert(0, REPO)
    from unicore_tpu.data import IndexedRecordWriter

    rng = np.random.RandomState(0)
    words = ["tok%d" % i for i in range(30)]
    with open(os.path.join(data_dir, "dict.txt"), "w") as f:
        for w in words:
            f.write(f"{w} 1\n")
    for split, n in (("train", 48), ("valid", 8)):
        with IndexedRecordWriter(os.path.join(data_dir, split + ".rec")) as w:
            for _ in range(n):
                L = rng.randint(6, 24)
                # learnable structure: short repeating n-grams
                seq = [words[i % 7] for i in range(L)]
                w.write(seq)
    return data_dir


def test_lm_cli_trains_and_loss_decreases(corpus, tmp_path):
    save_dir = str(tmp_path / "ckpt")
    cmd = [
        sys.executable, "-m", "unicore_tpu_cli.train", corpus,
        "--user-dir", os.path.join(REPO, "examples", "lm"),
        "--task", "lm", "--loss", "lm_cross_entropy",
        "--arch", "transformer_lm",
        "--decoder-layers", "1", "--decoder-embed-dim", "32",
        "--decoder-ffn-embed-dim", "64", "--decoder-attention-heads", "2",
        "--max-seq-len", "32", "--batch-size", "8",
        "--optimizer", "adam", "--lr", "5e-3", "--lr-scheduler", "fixed",
        "--max-update", "16", "--log-interval", "4", "--log-format", "simple",
        "--save-dir", save_dir,
        "--required-batch-size-multiple", "1", "--num-workers", "0", "--cpu",
    ]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=560, env=env, cwd=REPO
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "done training" in r.stdout
    assert "ppl" in r.stdout  # user-dir loss's derived metric surfaced
    assert os.path.exists(os.path.join(save_dir, "checkpoint_last.pt"))

    import re

    losses = [
        float(m) for m in re.findall(r"\| loss ([\d.]+) \|", r.stdout)
    ]
    assert len(losses) >= 2 and losses[-1] < losses[0], losses
