"""Sequence-parallel attention (ring / Ulysses) vs single-device full
attention, on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu.parallel import ring_self_attention, ulysses_attention


def full_attention(q, k, v, bias=None, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
    if bias is not None:
        s = s + bias
    if causal:
        t = q.shape[1]
        s = s + jnp.triu(jnp.full((t, t), -1e30), k=1)[None, None]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    return jax.sharding.Mesh(np.asarray(devs[:8]).reshape(8), ("seq",))


@pytest.fixture
def qkv(rng):
    B, T, H, D = 2, 64, 8, 16
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_module_causal_under_seq_parallel(rng, mesh, qkv, impl):
    """The decoder path under an active seq mesh axis: causal=True must
    flow to ring/Ulysses natively (NOT as a merged -inf bias — an all--inf
    remote score block would NaN the ring's online softmax)."""
    from unicore_tpu import parallel
    from unicore_tpu.modules import SelfMultiheadAttention

    B, T, H, D = 2, 64, 8, 16
    x = jnp.asarray(rng.randn(B, T, H * D).astype(np.float32))
    attn = SelfMultiheadAttention(embed_dim=H * D, num_heads=H, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), x)
    o_ref = attn.apply(params, x, causal=True)
    parallel.enable_sequence_parallel(mesh, impl=impl)
    try:
        o_sp = attn.apply(params, x, causal=True)
    finally:
        parallel.disable_sequence_parallel()
    assert np.isfinite(np.asarray(o_sp)).all()
    np.testing.assert_allclose(
        np.asarray(o_ref), np.asarray(o_sp), atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(rng, mesh, qkv, causal):
    q, k, v = qkv
    out = ring_self_attention(mesh, q, k, v, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_with_bias(rng, mesh, qkv):
    q, k, v = qkv
    T, H = q.shape[1], q.shape[2]
    bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
    out = ring_self_attention(mesh, q, k, v, bias=bias)
    ref = full_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_grads(rng, mesh, qkv):
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(mesh, q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=name
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(rng, mesh, qkv, causal):
    q, k, v = qkv
    from jax.sharding import PartitionSpec as P

    spec = P(None, "seq", None, None)
    wrapped = jax.shard_map(
        lambda q_, k_, v_: ulysses_attention(
            q_, k_, v_, axis_name="seq", causal=causal
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    out = wrapped(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_key_padding_mask(rng, mesh, qkv):
    q, k, v = qkv
    B, T = q.shape[0], q.shape[1]
    pad = np.zeros((B, T), dtype=bool)
    pad[:, T - 10:] = True  # last 10 keys padded
    ref = full_attention(
        q, k, v,
        bias=jnp.where(jnp.asarray(pad)[:, None, None, :], -1e30, 0.0),
    )
    out = ring_self_attention(mesh, q, k, v, key_padding_mask=jnp.asarray(pad))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_key_padding_mask_headdim1_bias(rng, mesh, qkv):
    """Ulysses with a padding mask and NO per-head bias (the case that used
    to crash on the head-dim-1 slice)."""
    from unicore_tpu.parallel import ulysses_self_attention

    q, k, v = qkv
    B, T = q.shape[0], q.shape[1]
    pad = np.zeros((B, T), dtype=bool)
    pad[:, T - 6:] = True
    ref = full_attention(
        q, k, v,
        bias=jnp.where(jnp.asarray(pad)[:, None, None, :], -1e30, 0.0),
    )
    out = ulysses_self_attention(
        mesh, q, k, v, key_padding_mask=jnp.asarray(pad)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_seq_parallel_attention_dropout_fails_fast(rng, mesh, qkv):
    """attention_dropout > 0 under sequence parallelism is an error unless
    the dropout skip is explicitly accepted (advisor r2: silent
    regularization loss must not scroll by as a one-line warning)."""
    from unicore_tpu import parallel
    from unicore_tpu.modules import multihead_attention as mha

    q, k, v = qkv
    devs = jax.devices()
    mesh = jax.sharding.Mesh(
        np.asarray(devs[:8]).reshape(1, 1, 8), ("data", "fsdp", "seq")
    )
    parallel.enable_sequence_parallel(mesh, "ring")
    try:
        with pytest.raises(ValueError, match="attention_dropout"):
            mha._seq_parallel_attend(
                q, k, v, scaling=0.25, dropout=0.1,
                key_padding_mask=None, bias=None,
            )
        # explicit opt-in: no raise, dropout skipped
        parallel.enable_sequence_parallel(mesh, "ring", allow_dropout_skip=True)
        out = mha._seq_parallel_attend(
            q, k, v, scaling=0.25, dropout=0.1,
            key_padding_mask=None, bias=None,
        )
        assert out is not None
    finally:
        parallel.disable_sequence_parallel()
