"""Sequence-parallel attention (ring / Ulysses) vs single-device full
attention, on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unicore_tpu.parallel import ring_self_attention, ulysses_attention


def full_attention(q, k, v, bias=None, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
    if bias is not None:
        s = s + bias
    if causal:
        t = q.shape[1]
        s = s + jnp.triu(jnp.full((t, t), -1e30), k=1)[None, None]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    return jax.sharding.Mesh(np.asarray(devs[:8]).reshape(8), ("seq",))


@pytest.fixture
def qkv(rng):
    B, T, H, D = 2, 64, 8, 16
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_module_causal_under_seq_parallel(rng, mesh, qkv, impl):
    """The decoder path under an active seq mesh axis: causal=True must
    flow to ring/Ulysses natively (NOT as a merged -inf bias — an all--inf
    remote score block would NaN the ring's online softmax)."""
    from unicore_tpu import parallel
    from unicore_tpu.modules import SelfMultiheadAttention

    B, T, H, D = 2, 64, 8, 16
    x = jnp.asarray(rng.randn(B, T, H * D).astype(np.float32))
    attn = SelfMultiheadAttention(embed_dim=H * D, num_heads=H, dropout=0.0)
    params = attn.init(jax.random.PRNGKey(0), x)
    o_ref = attn.apply(params, x, causal=True)
    parallel.enable_sequence_parallel(mesh, impl=impl)
    try:
        o_sp = attn.apply(params, x, causal=True)
    finally:
        parallel.disable_sequence_parallel()
    assert np.isfinite(np.asarray(o_sp)).all()
    np.testing.assert_allclose(
        np.asarray(o_ref), np.asarray(o_sp), atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(rng, mesh, qkv, causal):
    q, k, v = qkv
    out = ring_self_attention(mesh, q, k, v, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_with_bias(rng, mesh, qkv):
    q, k, v = qkv
    T, H = q.shape[1], q.shape[2]
    bias = jnp.asarray(rng.randn(1, H, T, T).astype(np.float32))
    out = ring_self_attention(mesh, q, k, v, bias=bias)
    ref = full_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_grads(rng, mesh, qkv):
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(mesh, q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=name
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(rng, mesh, qkv, causal):
    q, k, v = qkv
    from jax.sharding import PartitionSpec as P

    from unicore_tpu.parallel._compat import shard_map

    spec = P(None, "seq", None, None)
    wrapped = shard_map(
        lambda q_, k_, v_: ulysses_attention(
            q_, k_, v_, axis_name="seq", causal=causal
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    out = wrapped(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_key_padding_mask(rng, mesh, qkv):
    q, k, v = qkv
    B, T = q.shape[0], q.shape[1]
    pad = np.zeros((B, T), dtype=bool)
    pad[:, T - 10:] = True  # last 10 keys padded
    ref = full_attention(
        q, k, v,
        bias=jnp.where(jnp.asarray(pad)[:, None, None, :], -1e30, 0.0),
    )
    out = ring_self_attention(mesh, q, k, v, key_padding_mask=jnp.asarray(pad))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_key_padding_mask_headdim1_bias(rng, mesh, qkv):
    """Ulysses with a padding mask and NO per-head bias (the case that used
    to crash on the head-dim-1 slice)."""
    from unicore_tpu.parallel import ulysses_self_attention

    q, k, v = qkv
    B, T = q.shape[0], q.shape[1]
    pad = np.zeros((B, T), dtype=bool)
    pad[:, T - 6:] = True
    ref = full_attention(
        q, k, v,
        bias=jnp.where(jnp.asarray(pad)[:, None, None, :], -1e30, 0.0),
    )
    out = ulysses_self_attention(
        mesh, q, k, v, key_padding_mask=jnp.asarray(pad)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# ---------------------------------------------------------------------------
# attention dropout on the sequence-parallel paths (VERDICT r3 next-5):
# ring derives masks from global block identity, Ulysses decorrelates per
# head-shard device — the escape hatch is retired
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_seq_parallel_dropout_statistics(rng, mesh, qkv, impl):
    """With v = ones, dropout(softmax) rows sum to ~1 in expectation (the
    1/(1-p) rescale is exact in the mean); p=0 reproduces the
    deterministic path; the mask is deterministic per rng and changes
    with it."""
    from unicore_tpu.parallel import ring_self_attention, ulysses_self_attention

    q, k, v = qkv
    ones = jnp.ones_like(v)
    attend = ring_self_attention if impl == "ring" else ulysses_self_attention
    key = jax.random.PRNGKey(3)

    out0 = attend(mesh, q, k, ones, dropout_p=0.0, rng=key)
    ref = full_attention(q, k, ones)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(ref), atol=1e-5)

    out1 = attend(mesh, q, k, ones, dropout_p=0.3, rng=key)
    out1b = attend(mesh, q, k, ones, dropout_p=0.3, rng=key)
    out2 = attend(mesh, q, k, ones, dropout_p=0.3, rng=jax.random.PRNGKey(4))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out1b))
    assert not np.allclose(np.asarray(out1), np.asarray(out2))
    # expectation: every entry of out1 estimates 1 (row mass)
    m = float(np.mean(np.asarray(out1)))
    assert abs(m - 1.0) < 0.1, m
    # and it is a real mask (row masses vary)
    assert float(np.std(np.asarray(out1))) > 0.01


def test_ulysses_dropout_decorrelates_head_shards(rng, mesh):
    """All heads get IDENTICAL q/k/v; with per-device seed offsets the
    sampled masks must still differ across head-shard devices (without
    the offset, local head index 0 on every device would repeat the same
    mask for different global heads)."""
    from unicore_tpu.parallel import ulysses_self_attention

    B, T, H, D = 2, 64, 8, 16
    one_head = rng.randn(B, T, 1, D).astype(np.float32)
    mk = lambda: jnp.asarray(np.repeat(one_head, H, axis=2))
    q, k = mk(), mk()
    ones = jnp.ones((B, T, H, D), jnp.float32)
    out = ulysses_self_attention(
        mesh, q, k, ones, dropout_p=0.4, rng=jax.random.PRNGKey(5)
    )
    out = np.asarray(out)  # [B, T, H, D]
    for h in range(1, H):
        assert not np.allclose(out[:, :, 0], out[:, :, h]), (
            f"head {h} mask duplicates head 0's"
        )


def test_ring_dropout_grads_finite(rng, mesh, qkv):
    from unicore_tpu.parallel import ring_self_attention

    q, k, v = qkv

    def loss(q, k, v):
        return jnp.sum(
            ring_self_attention(
                mesh, q, k, v, dropout_p=0.2, rng=jax.random.PRNGKey(0)
            ) ** 2
        )

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a in g:
        assert np.isfinite(np.asarray(a)).all()


def test_module_seq_parallel_dropout_no_raise(rng, mesh, qkv):
    """attention_dropout > 0 under sequence parallelism now WORKS (the
    r2/r3 fail-fast + --seq-parallel-skip-attention-dropout hatch is
    retired)."""
    from unicore_tpu import parallel
    from unicore_tpu.modules import multihead_attention as mha

    q, k, v = qkv
    devs = jax.devices()
    mesh3 = jax.sharding.Mesh(
        np.asarray(devs[:8]).reshape(1, 1, 8), ("data", "fsdp", "seq")
    )
    parallel.enable_sequence_parallel(mesh3, "ring")
    try:
        out = mha._seq_parallel_attend(
            q, k, v, scaling=0.25, dropout=0.1,
            key_padding_mask=None, bias=None, rng=jax.random.PRNGKey(0),
        )
        assert out is not None and np.isfinite(np.asarray(out)).all()
    finally:
        parallel.disable_sequence_parallel()
