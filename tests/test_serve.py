"""Serve tier (unicore_tpu/serve): KV-pool invariants, paged-attention
parity (eager + Pallas-interpret), scheduler properties under forced
eviction, engine batched correctness, and seeded-sampling determinism.

The load-bearing property everywhere: for ANY admission/eviction trace,
every request's emitted tokens are IDENTICAL to decoding that request
alone via the plain full-forward path — continuous batching and paging
are pure capacity features, never accuracy features."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from examples.lm.model import TransformerLMModel
from unicore_tpu.serve import PagedKVPool, PoolExhausted, Request
from unicore_tpu.serve.engine import ServeEngine

V, D, H, F, L = 29, 32, 4, 64, 2
PAD = 0


@pytest.fixture(scope="module")
def lm():
    model = TransformerLMModel(
        vocab_size=V, padding_idx=PAD, decoder_layers=L,
        decoder_embed_dim=D, decoder_ffn_embed_dim=F,
        decoder_attention_heads=H, max_seq_len=64,
        emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, rel_pos=False, abs_pos=False, rotary=True,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def solo_greedy(model, params, prompt, n_new, eos=None):
    """The oracle: full-forward greedy decode of one request alone."""
    toks = jnp.asarray(prompt, jnp.int32)[None]
    out = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, toks)
        nxt = int(np.asarray(jnp.argmax(logits[0, -1])))
        out.append(nxt)
        if eos is not None and nxt == eos:
            break
        toks = jnp.concatenate(
            [toks, jnp.asarray([[nxt]], jnp.int32)], axis=1
        )
    return out


# -- KV pool invariants ----------------------------------------------------


def test_pool_alloc_free_round_trip():
    pool = PagedKVPool(num_pages=8, page_size=4)
    assert pool.num_usable_pages == 7  # page 0 reserved (trash)
    a = pool.alloc("a", 9)   # 3 pages
    b = pool.alloc("b", 4)   # 1 page
    pool.check_invariants()
    assert len(a) == 3 and len(b) == 1
    assert 0 not in a + b
    assert not set(a) & set(b), "page aliased across sequences"
    assert pool.occupancy() == pytest.approx(4 / 7)
    pool.free("a")
    pool.check_invariants()
    assert pool.num_free_pages == 6
    c = pool.alloc("c", 24)  # 6 pages: reuses a's pages, still disjoint
    pool.check_invariants()
    assert not set(c) & set(b)
    pool.free("b")
    pool.free("c")
    pool.check_invariants()
    assert pool.num_free_pages == 7 and pool.occupancy() == 0.0


def test_pool_extend_slots_and_page_order():
    pool = PagedKVPool(num_pages=8, page_size=4)
    pool.alloc("s", 3)
    table = pool.page_table("s")
    assert pool.slot("s", 0) == table[0] * 4
    assert pool.slot("s", 2) == table[0] * 4 + 2
    pool.extend("s", 1)  # fills the page, no new alloc
    assert pool.page_table("s") == table
    pool.extend("s", 1)  # crosses the boundary
    t2 = pool.page_table("s")
    assert t2[:1] == table and len(t2) == 2
    assert pool.slot("s", 4) == t2[1] * 4
    with pytest.raises(IndexError):
        pool.slot("s", 8)  # beyond the allocated pages
    pool.check_invariants()


def test_pool_exhaustion_and_double_free():
    pool = PagedKVPool(num_pages=4, page_size=2)  # 3 usable
    pool.alloc("a", 4)
    with pytest.raises(PoolExhausted):
        pool.alloc("b", 5)  # needs 3, only 1 free
    pool.check_invariants()  # failed alloc must not leak
    pool.alloc("b", 2)
    with pytest.raises(PoolExhausted):
        pool.extend("b", 1)
    pool.free("b")
    with pytest.raises(KeyError):
        pool.free("b")
    with pytest.raises(ValueError):
        PagedKVPool(num_pages=1, page_size=4)  # no room for the trash page


# -- paged attention parity ------------------------------------------------


def _random_paged_case(rng, B=3, P=5, ps=4, heads=4, d=16):
    num_pages = B * P + 1
    pool_k = jnp.asarray(rng.randn(num_pages * ps, heads, d), jnp.float32)
    pool_v = jnp.asarray(rng.randn(num_pages * ps, heads, d), jnp.float32)
    perm = rng.permutation(num_pages - 1)[: B * P] + 1
    table = jnp.asarray(perm.reshape(B, P).astype(np.int32))
    lengths = jnp.asarray(rng.randint(1, P * ps + 1, size=(B,)), jnp.int32)
    return pool_k, pool_v, table, lengths


def test_paged_attention_eager_matches_dense(rng):
    """Gathering pages in table order must reproduce plain causal
    attention over each sequence's contiguous KV."""
    from unicore_tpu.serve.attention import paged_attention_reference

    B, P, ps, heads, d = 3, 5, 4, 4, 16
    pool_k, pool_v, table, lengths = _random_paged_case(rng, B, P, ps,
                                                       heads, d)
    q = jnp.asarray(rng.randn(B, 1, heads, d), jnp.float32)
    scale = d ** -0.5
    got = paged_attention_reference(
        q, pool_k, pool_v, table, (lengths - 1)[:, None], lengths, ps,
        scale,
    )
    from unicore_tpu.serve.attention import gather_slots

    k_seq = gather_slots(pool_k, table, ps)
    v_seq = gather_slots(pool_v, table, ps)
    for b in range(B):
        n = int(lengths[b])
        s = jnp.einsum(
            "qhd,khd->hqk", q[b] * scale, k_seq[b, :n]
        ).astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        want = jnp.einsum("hqk,khd->qhd", p, v_seq[b, :n])
        np.testing.assert_allclose(
            np.asarray(got[b]), np.asarray(want), atol=1e-5, rtol=1e-5
        )


@pytest.mark.parametrize("pages_per_block", [1, 2, 3])
def test_ragged_kernel_matches_eager(rng, pages_per_block):
    """Pallas ragged decode kernel (interpret mode on CPU) vs the eager
    gather path, including ragged lengths and an inactive (length-0)
    row."""
    from unicore_tpu.ops.pallas.paged_attention import (
        ragged_decode_attention,
    )
    from unicore_tpu.serve.attention import paged_attention_reference

    B, P, ps, heads, d = 4, 5, 4, 4, 16
    pool_k, pool_v, table, lengths = _random_paged_case(rng, B, P, ps,
                                                       heads, d)
    lengths = lengths.at[2].set(0)  # inactive batch slot
    q = jnp.asarray(rng.randn(B, 1, heads, d), jnp.float32)
    scale = d ** -0.5
    ref = paged_attention_reference(
        q, pool_k, pool_v, table, (lengths - 1)[:, None], lengths, ps,
        scale,
    )
    out = ragged_decode_attention(
        q, pool_k, pool_v, table, lengths, page_size=ps, scale=scale,
        pages_per_block=pages_per_block,
    )
    assert bool(jnp.isfinite(out).all())
    active = np.asarray(lengths) > 0
    np.testing.assert_allclose(
        np.asarray(out)[active], np.asarray(ref)[active],
        atol=2e-5, rtol=2e-5,
    )


# -- engine batched correctness (the PR acceptance property) ---------------


@pytest.mark.slow  # ~67s (8 solo-oracle full forwards); tier-1 keeps
# the scheduler property traces, parity, and pool-exhausted recovery
# tests; CI's full suite + serve smoke run this acceptance oracle
def test_engine_mixed_batch_matches_solo_decode(lm, rng):
    """>= 8 requests, mixed prompt lengths, pool sized to force
    eviction at least once: every emitted sequence must be
    token-identical to its solo full-forward greedy decode."""
    model, params = lm
    engine = ServeEngine(
        model, params, num_pages=9, page_size=4, max_batch=4,
        chaos_rate=0.25, chaos_rng=random.Random(7),
    )
    lens = [3, 5, 7, 4, 9, 6, 8, 5]
    reqs = [
        Request(
            prompt=rng.randint(1, V, size=(n,)).tolist(),
            max_new_tokens=8, seed=i, eos_id=5, request_id=f"r{i}",
        )
        for i, n in enumerate(lens)
    ]
    results = engine.generate(reqs)
    assert engine.stats["evictions"] >= 1, (
        "the test must exercise eviction; shrink the pool or raise "
        "chaos_rate"
    )
    assert [r.request_id for r in results] == [f"r{i}"
                                               for i in range(len(lens))]
    for res, req in zip(results, reqs):
        want = solo_greedy(model, params, req.prompt, req.max_new_tokens,
                           eos=req.eos_id)
        assert res.tokens == want, (req.prompt, res.tokens, want)
        assert res.finish_reason in ("eos", "length")
        assert res.ttft_ms >= 0.0
    assert engine.stats["peak_pool_occupancy"] > 0.5


def test_scheduler_admit_race_returns_partial_or_reraises_empty():
    """The accounting race inside admit() (can_alloc said yes,
    alloc raised anyway): with earlier admissions in the same call the
    partial batch is RETURNED (so the engine prefills them — an escape
    would strand allocated-but-never-prefilled KV pages in `running`),
    and only an empty admission re-raises for the engine's recovery.
    Either way the raced sequence stays at waiting[0]."""
    from unicore_tpu.serve.scheduler import Scheduler

    pool = PagedKVPool(num_pages=16, page_size=4)
    sched = Scheduler(pool, max_batch=4, prefill_token_budget=64)
    for i in range(3):
        sched.add(Request(prompt=[1] * 6, max_new_tokens=2,
                          seed=i, request_id=f"r{i}"))
    real_can_alloc, lies = pool.can_alloc, {"calls": 0}

    def lie_on_second(n):  # 2nd admission's alloc hits the race
        lies["calls"] += 1
        return True if lies["calls"] == 2 else real_can_alloc(n)

    real_alloc = pool.alloc

    def alloc(sid, n):
        if lies["calls"] == 2 and not real_can_alloc(n):
            raise PoolExhausted("raced")
        return real_alloc(sid, n)

    pool.can_alloc, pool.alloc = lie_on_second, alloc
    del pool._free[:-2]  # 2 free pages left: fits ONE 6-token prompt
    admitted = sched.admit()
    assert [s.req.request_id for s in admitted] == ["r0"], admitted
    assert sched.waiting[0].req.request_id == "r1", "raced seq lost"
    assert [s.req.request_id for s in sched.running] == ["r0"]
    # empty admission: the race now escapes (the engine's recovery path)
    lies["calls"] = 1  # next can_alloc call lies again
    with pytest.raises(PoolExhausted):
        sched.admit()
    assert sched.waiting[0].req.request_id == "r1", "raced seq lost"
    assert [s.req.request_id for s in sched.running] == ["r0"]


def test_engine_recovers_from_pool_exhausted_admission_race(lm):
    """A PoolExhausted that escapes admit() (which, per the scheduler
    contract above, means NOTHING was admitted in that call) must not
    escape the engine: it preempts the scheduler's LIFO victim, counts
    ``pool_exhausted_recoveries``, re-admits the still-queued sequence,
    and every request's tokens remain identical to solo decode — the
    race is a capacity hiccup, never an accuracy or liveness event."""
    model, params = lm
    engine = ServeEngine(
        model, params, num_pages=7, page_size=4, max_batch=3,
        prefill_token_budget=16,
        chaos_rate=0.2, chaos_rng=random.Random(3),
    )
    sched = engine.scheduler
    real_admit, races = sched.admit, {"n": 0}

    def racing_admit(bucket=None):
        # the empty-admission escape, mid-run (a victim must exist)
        if races["n"] < 2 and sched.running and sched.waiting:
            races["n"] += 1
            raise PoolExhausted("admission race")
        return real_admit(bucket=bucket)

    sched.admit = racing_admit
    trng = np.random.RandomState(3)
    reqs = [
        Request(
            prompt=trng.randint(1, V, size=(int(n),)).tolist(),
            max_new_tokens=5, seed=i, eos_id=5, request_id=f"r{i}",
        )
        for i, n in enumerate([3, 7, 5, 8, 4])
    ]
    results = engine.generate(reqs)
    assert races["n"] == 2, "the race was never exercised"
    assert engine.stats["pool_exhausted_recoveries"] >= 1
    engine.pool.check_invariants()
    assert [r.request_id for r in results] == [f"r{i}" for i in range(5)]
    for res, req in zip(results, reqs):
        want = solo_greedy(model, params, req.prompt, req.max_new_tokens,
                           eos=req.eos_id)
        assert res.tokens == want, (req.prompt, res.tokens, want)


@pytest.mark.parametrize("chaos_seed", [11, 23])
def test_scheduler_property_random_traces(lm, chaos_seed):
    """Randomized admission/eviction traces (seeded chaos preemption on
    a tiny pool): outputs stay token-identical to solo decode — no
    request's tokens are lost or duplicated."""
    model, params = lm
    trng = np.random.RandomState(chaos_seed)
    engine = ServeEngine(
        model, params, num_pages=7, page_size=4, max_batch=3,
        prefill_token_budget=16,
        chaos_rate=0.4, chaos_rng=random.Random(chaos_seed),
    )
    reqs = [
        Request(
            prompt=trng.randint(1, V, size=(int(n),)).tolist(),
            max_new_tokens=int(m), seed=i, eos_id=5,
        )
        for i, (n, m) in enumerate(
            zip(trng.randint(1, 11, size=8), trng.randint(1, 7, size=8))
        )
    ]
    results = engine.generate(reqs)
    for res, req in zip(results, reqs):
        want = solo_greedy(model, params, req.prompt, req.max_new_tokens,
                           eos=req.eos_id)
        assert res.tokens == want, (req.prompt, res.tokens, want)


def test_engine_seeded_sampling_deterministic(lm):
    """Same seeds -> same sampled tokens, run to run, and eviction
    pressure must not change a sampled continuation (step keys fold in
    the absolute step index)."""
    model, params = lm
    prompts = [[3, 7, 2], [11, 4, 9, 8, 1], [6, 2], [13, 5, 5, 20]]

    def run(chaos):
        engine = ServeEngine(
            model, params, num_pages=8, page_size=4, max_batch=4,
            chaos_rate=0.5 if chaos else 0.0,
            chaos_rng=random.Random(3) if chaos else None,
        )
        reqs = [
            Request(prompt=p, max_new_tokens=6, temperature=0.8,
                    top_k=5, seed=100 + i)
            for i, p in enumerate(prompts)
        ]
        return [r.tokens for r in engine.generate(reqs)]

    base = run(chaos=False)
    assert all(len(toks) == 6 for toks in base)
    assert base == run(chaos=False), "same seeds must replay identically"
    assert base == run(chaos=True), (
        "eviction/re-prefill changed a seeded sampling stream"
    )


def test_engine_rejects_oversized_prompt(lm):
    model, params = lm
    engine = ServeEngine(model, params, num_pages=4, page_size=4,
                         max_batch=2)  # context = 12 slots
    with pytest.raises(ValueError, match="context"):
        engine.generate(
            [Request(prompt=list(range(1, 15)), max_new_tokens=2)]
        )


def test_engine_capacity_finish(lm):
    """A request bounded by pool capacity is truncated with reason
    "capacity" instead of wedging the scheduler — and the truncated
    tokens still match the solo decode."""
    model, params = lm
    engine = ServeEngine(model, params, num_pages=4, page_size=4,
                         max_batch=2)  # 12 usable slots = max_context
    [res] = engine.generate(
        [Request(prompt=[3, 7, 2, 9], max_new_tokens=20)]
    )
    assert res.finish_reason == "capacity"
    # the last decode writes KV at slot max_context-1 and samples one
    # final token beyond it: max_context - len(prompt) + 1 tokens
    assert len(res.tokens) == 12 - 4 + 1
    want = solo_greedy(model, params, [3, 7, 2, 9], len(res.tokens))
    assert res.tokens == want


# -- CLI -------------------------------------------------------------------


def test_serve_cli_demo(tmp_path):
    import json

    from unicore_tpu.serve.cli import main

    out = tmp_path / "serve.json"
    rc = main([
        "--demo", "--num-requests", "3", "--max-new-tokens", "5",
        "--page-size", "4", "--num-pages", "16", "--max-batch", "3",
        "--prompt-len-range", "3,9", "--json", str(out),
    ])
    assert rc == 0
    report = json.loads(out.read_text())
    assert len(report["results"]) == 3
    for res in report["results"]:
        assert res["finish_reason"] in ("eos", "length", "capacity")
        assert len(res["tokens"]) == 5
    assert report["stats"]["generated_tokens"] == 15
