"""Serve tier (unicore_tpu/serve): KV-pool invariants, paged-attention
parity (eager + Pallas-interpret), scheduler properties under forced
eviction, engine batched correctness, and seeded-sampling determinism.

The load-bearing property everywhere: for ANY admission/eviction trace,
every request's emitted tokens are IDENTICAL to decoding that request
alone via the plain full-forward path — continuous batching and paging
are pure capacity features, never accuracy features."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from examples.lm.model import TransformerLMModel
from unicore_tpu.serve import PagedKVPool, PoolExhausted, Request
from unicore_tpu.serve.engine import ServeEngine

V, D, H, F, L = 29, 32, 4, 64, 2
PAD = 0


@pytest.fixture(scope="module")
def lm():
    model = TransformerLMModel(
        vocab_size=V, padding_idx=PAD, decoder_layers=L,
        decoder_embed_dim=D, decoder_ffn_embed_dim=F,
        decoder_attention_heads=H, max_seq_len=64,
        emb_dropout=0.0, dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, rel_pos=False, abs_pos=False, rotary=True,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def solo_greedy(model, params, prompt, n_new, eos=None):
    """The oracle: full-forward greedy decode of one request alone."""
    toks = jnp.asarray(prompt, jnp.int32)[None]
    out = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, toks)
        nxt = int(np.asarray(jnp.argmax(logits[0, -1])))
        out.append(nxt)
        if eos is not None and nxt == eos:
            break
        toks = jnp.concatenate(
            [toks, jnp.asarray([[nxt]], jnp.int32)], axis=1
        )
    return out


# -- KV pool invariants ----------------------------------------------------


def test_pool_alloc_free_round_trip():
    pool = PagedKVPool(num_pages=8, page_size=4)
    assert pool.num_usable_pages == 7  # page 0 reserved (trash)
    a = pool.alloc("a", 9)   # 3 pages
    b = pool.alloc("b", 4)   # 1 page
    pool.check_invariants()
    assert len(a) == 3 and len(b) == 1
    assert 0 not in a + b
    assert not set(a) & set(b), "page aliased across sequences"
    assert pool.occupancy() == pytest.approx(4 / 7)
    pool.free("a")
    pool.check_invariants()
    assert pool.num_free_pages == 6
    c = pool.alloc("c", 24)  # 6 pages: reuses a's pages, still disjoint
    pool.check_invariants()
    assert not set(c) & set(b)
    pool.free("b")
    pool.free("c")
    pool.check_invariants()
    assert pool.num_free_pages == 7 and pool.occupancy() == 0.0


def test_pool_extend_slots_and_page_order():
    pool = PagedKVPool(num_pages=8, page_size=4)
    pool.alloc("s", 3)
    table = pool.page_table("s")
    assert pool.slot("s", 0) == table[0] * 4
    assert pool.slot("s", 2) == table[0] * 4 + 2
    pool.extend("s", 1)  # fills the page, no new alloc
    assert pool.page_table("s") == table
    pool.extend("s", 1)  # crosses the boundary
    t2 = pool.page_table("s")
    assert t2[:1] == table and len(t2) == 2
    assert pool.slot("s", 4) == t2[1] * 4
    with pytest.raises(IndexError):
        pool.slot("s", 8)  # beyond the allocated pages
    pool.check_invariants()


def test_pool_exhaustion_and_double_free():
    pool = PagedKVPool(num_pages=4, page_size=2)  # 3 usable
    pool.alloc("a", 4)
    with pytest.raises(PoolExhausted):
        pool.alloc("b", 5)  # needs 3, only 1 free
    pool.check_invariants()  # failed alloc must not leak
    pool.alloc("b", 2)
    with pytest.raises(PoolExhausted):
        pool.extend("b", 1)
    pool.free("b")
    with pytest.raises(KeyError):
        pool.free("b")
    with pytest.raises(ValueError):
        PagedKVPool(num_pages=1, page_size=4)  # no room for the trash page


# -- paged attention parity ------------------------------------------------


def _random_paged_case(rng, B=3, P=5, ps=4, heads=4, d=16):
    num_pages = B * P + 1
    pool_k = jnp.asarray(rng.randn(num_pages * ps, heads, d), jnp.float32)
    pool_v = jnp.asarray(rng.randn(num_pages * ps, heads, d), jnp.float32)
    perm = rng.permutation(num_pages - 1)[: B * P] + 1
    table = jnp.asarray(perm.reshape(B, P).astype(np.int32))
    lengths = jnp.asarray(rng.randint(1, P * ps + 1, size=(B,)), jnp.int32)
    return pool_k, pool_v, table, lengths


def test_paged_attention_eager_matches_dense(rng):
    """Gathering pages in table order must reproduce plain causal
    attention over each sequence's contiguous KV."""
    from unicore_tpu.serve.attention import paged_attention_reference

    B, P, ps, heads, d = 3, 5, 4, 4, 16
    pool_k, pool_v, table, lengths = _random_paged_case(rng, B, P, ps,
                                                       heads, d)
    q = jnp.asarray(rng.randn(B, 1, heads, d), jnp.float32)
    scale = d ** -0.5
    got = paged_attention_reference(
        q, pool_k, pool_v, table, (lengths - 1)[:, None], lengths, ps,
        scale,
    )
    from unicore_tpu.serve.attention import gather_slots

    k_seq = gather_slots(pool_k, table, ps)
    v_seq = gather_slots(pool_v, table, ps)
    for b in range(B):
        n = int(lengths[b])
        s = jnp.einsum(
            "qhd,khd->hqk", q[b] * scale, k_seq[b, :n]
        ).astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        want = jnp.einsum("hqk,khd->qhd", p, v_seq[b, :n])
        np.testing.assert_allclose(
            np.asarray(got[b]), np.asarray(want), atol=1e-5, rtol=1e-5
        )


@pytest.mark.parametrize("pages_per_block", [1, 2, 3])
def test_ragged_kernel_matches_eager(rng, pages_per_block):
    """Pallas ragged kernel (interpret mode on CPU) vs the eager gather
    path on a MIXED batch — a decode row, prefill-chunk rows of
    different widths, ragged lengths, and an inactive (length-0) row
    all in one dispatch (the unified serve-step shape)."""
    from unicore_tpu.ops.pallas.paged_attention import (
        ragged_paged_attention,
    )
    from unicore_tpu.serve.attention import paged_attention_reference

    B, P, ps, heads, d, T = 4, 5, 4, 4, 16, 3
    pool_k, pool_v, table, lengths = _random_paged_case(rng, B, P, ps,
                                                       heads, d)
    lengths = lengths.at[2].set(0)  # inactive batch slot
    ln = np.asarray(lengths)
    positions = np.full((B, T), -1, np.int32)
    positions[0] = [ln[0] - 3, ln[0] - 2, ln[0] - 1]  # prefill chunk
    positions[1, 0] = ln[1] - 1                       # decode row
    positions[3, :2] = [ln[3] - 2, ln[3] - 1]         # short chunk
    positions = jnp.asarray(positions)
    q = jnp.asarray(rng.randn(B, T, heads, d), jnp.float32)
    scale = d ** -0.5
    ref = paged_attention_reference(
        q, pool_k, pool_v, table, positions, lengths, ps, scale,
    )
    out = ragged_paged_attention(
        q, pool_k, pool_v, table, positions, lengths, page_size=ps,
        scale=scale, pages_per_block=pages_per_block,
    )
    assert bool(jnp.isfinite(out).all())  # padded rows finite too
    active = np.asarray(positions) >= 0
    np.testing.assert_allclose(
        np.asarray(out)[active], np.asarray(ref)[active],
        atol=2e-5, rtol=2e-5,
    )


def test_ragged_decode_wrapper_matches_eager(rng):
    """The T=1 decode wrapper stays available and exact."""
    from unicore_tpu.ops.pallas.paged_attention import (
        ragged_decode_attention,
    )
    from unicore_tpu.serve.attention import paged_attention_reference

    B, P, ps, heads, d = 4, 5, 4, 4, 16
    pool_k, pool_v, table, lengths = _random_paged_case(rng, B, P, ps,
                                                       heads, d)
    q = jnp.asarray(rng.randn(B, 1, heads, d), jnp.float32)
    scale = d ** -0.5
    ref = paged_attention_reference(
        q, pool_k, pool_v, table, (lengths - 1)[:, None], lengths, ps,
        scale,
    )
    out = ragged_decode_attention(
        q, pool_k, pool_v, table, lengths, page_size=ps, scale=scale,
        pages_per_block=2,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5,
    )


# -- engine batched correctness (the PR acceptance property) ---------------


@pytest.mark.slow  # ~67s (8 solo-oracle full forwards); tier-1 keeps
# the scheduler property traces, parity, and pool-exhausted recovery
# tests; CI's full suite + serve smoke run this acceptance oracle
def test_engine_mixed_batch_matches_solo_decode(lm, rng):
    """>= 8 requests, mixed prompt lengths, pool sized to force
    eviction at least once: every emitted sequence must be
    token-identical to its solo full-forward greedy decode."""
    model, params = lm
    engine = ServeEngine(
        model, params, num_pages=9, page_size=4, max_batch=4,
        chaos_rate=0.25, chaos_rng=random.Random(7),
    )
    lens = [3, 5, 7, 4, 9, 6, 8, 5]
    reqs = [
        Request(
            prompt=rng.randint(1, V, size=(n,)).tolist(),
            max_new_tokens=8, seed=i, eos_id=5, request_id=f"r{i}",
        )
        for i, n in enumerate(lens)
    ]
    results = engine.generate(reqs)
    assert engine.stats["evictions"] >= 1, (
        "the test must exercise eviction; shrink the pool or raise "
        "chaos_rate"
    )
    assert [r.request_id for r in results] == [f"r{i}"
                                               for i in range(len(lens))]
    for res, req in zip(results, reqs):
        want = solo_greedy(model, params, req.prompt, req.max_new_tokens,
                           eos=req.eos_id)
        assert res.tokens == want, (req.prompt, res.tokens, want)
        assert res.finish_reason in ("eos", "length")
        assert res.ttft_ms >= 0.0
    assert engine.stats["peak_pool_occupancy"] > 0.5


def test_scheduler_admit_race_returns_partial_or_reraises_empty():
    """The accounting race inside admit() (can_alloc said yes,
    alloc raised anyway): with earlier admissions in the same call the
    partial batch is RETURNED (so the engine prefills them — an escape
    would strand allocated-but-never-prefilled KV pages in `running`),
    and only an empty admission re-raises for the engine's recovery.
    Either way the raced sequence stays at waiting[0]."""
    from unicore_tpu.serve.scheduler import Scheduler

    pool = PagedKVPool(num_pages=16, page_size=4)
    sched = Scheduler(pool, max_batch=4, prefill_token_budget=64)
    for i in range(3):
        sched.add(Request(prompt=[1] * 6, max_new_tokens=2,
                          seed=i, request_id=f"r{i}"))
    real_can_alloc, lies = pool.can_alloc, {"calls": 0}

    def lie_on_second(n, tokens=None):  # 2nd admission's alloc races
        lies["calls"] += 1
        return True if lies["calls"] == 2 else real_can_alloc(n)

    real_alloc = pool.alloc

    def alloc(sid, n, tokens=None):
        if lies["calls"] == 2 and not real_can_alloc(n):
            raise PoolExhausted("raced")
        return real_alloc(sid, n, tokens=tokens)

    pool.can_alloc, pool.alloc = lie_on_second, alloc
    del pool._free[:-2]  # 2 free pages left: fits ONE 6-token prompt
    admitted = sched.admit()
    assert [s.req.request_id for s in admitted] == ["r0"], admitted
    assert sched.waiting[0].req.request_id == "r1", "raced seq lost"
    assert [s.req.request_id for s in sched.running] == ["r0"]
    # empty admission: the race now escapes (the engine's recovery path)
    lies["calls"] = 1  # next can_alloc call lies again
    with pytest.raises(PoolExhausted):
        sched.admit()
    assert sched.waiting[0].req.request_id == "r1", "raced seq lost"
    assert [s.req.request_id for s in sched.running] == ["r0"]


def test_engine_recovers_from_pool_exhausted_admission_race(lm):
    """A PoolExhausted that escapes admit() (which, per the scheduler
    contract above, means NOTHING was admitted in that call) must not
    escape the engine: it preempts the scheduler's LIFO victim, counts
    ``pool_exhausted_recoveries``, re-admits the still-queued sequence,
    and every request's tokens remain identical to solo decode — the
    race is a capacity hiccup, never an accuracy or liveness event."""
    model, params = lm
    engine = ServeEngine(
        model, params, num_pages=7, page_size=4, max_batch=3,
        prefill_token_budget=16,
        chaos_rate=0.2, chaos_rng=random.Random(3),
    )
    sched = engine.scheduler
    real_admit, races = sched.admit, {"n": 0}

    def racing_admit(bucket=None):
        # the empty-admission escape, mid-run (a victim must exist)
        if races["n"] < 2 and sched.running and sched.waiting:
            races["n"] += 1
            raise PoolExhausted("admission race")
        return real_admit(bucket=bucket)

    sched.admit = racing_admit
    trng = np.random.RandomState(3)
    reqs = [
        Request(
            prompt=trng.randint(1, V, size=(int(n),)).tolist(),
            max_new_tokens=5, seed=i, eos_id=5, request_id=f"r{i}",
        )
        for i, n in enumerate([3, 7, 5, 8, 4])
    ]
    results = engine.generate(reqs)
    assert races["n"] == 2, "the race was never exercised"
    assert engine.stats["pool_exhausted_recoveries"] >= 1
    engine.pool.check_invariants()
    assert [r.request_id for r in results] == [f"r{i}" for i in range(5)]
    for res, req in zip(results, reqs):
        want = solo_greedy(model, params, req.prompt, req.max_new_tokens,
                           eos=req.eos_id)
        assert res.tokens == want, (req.prompt, res.tokens, want)


@pytest.mark.parametrize("chaos_seed", [11, 23])
def test_scheduler_property_random_traces(lm, chaos_seed):
    """Randomized admission/eviction traces (seeded chaos preemption on
    a tiny pool): outputs stay token-identical to solo decode — no
    request's tokens are lost or duplicated."""
    model, params = lm
    trng = np.random.RandomState(chaos_seed)
    engine = ServeEngine(
        model, params, num_pages=7, page_size=4, max_batch=3,
        prefill_token_budget=16,
        chaos_rate=0.4, chaos_rng=random.Random(chaos_seed),
    )
    reqs = [
        Request(
            prompt=trng.randint(1, V, size=(int(n),)).tolist(),
            max_new_tokens=int(m), seed=i, eos_id=5,
        )
        for i, (n, m) in enumerate(
            zip(trng.randint(1, 11, size=8), trng.randint(1, 7, size=8))
        )
    ]
    results = engine.generate(reqs)
    for res, req in zip(results, reqs):
        want = solo_greedy(model, params, req.prompt, req.max_new_tokens,
                           eos=req.eos_id)
        assert res.tokens == want, (req.prompt, res.tokens, want)


def test_engine_seeded_sampling_deterministic(lm):
    """Same seeds -> same sampled tokens, run to run, and eviction
    pressure must not change a sampled continuation (step keys fold in
    the absolute step index)."""
    model, params = lm
    prompts = [[3, 7, 2], [11, 4, 9, 8, 1], [6, 2], [13, 5, 5, 20]]

    def run(chaos):
        engine = ServeEngine(
            model, params, num_pages=8, page_size=4, max_batch=4,
            chaos_rate=0.5 if chaos else 0.0,
            chaos_rng=random.Random(3) if chaos else None,
        )
        reqs = [
            Request(prompt=p, max_new_tokens=6, temperature=0.8,
                    top_k=5, seed=100 + i)
            for i, p in enumerate(prompts)
        ]
        return [r.tokens for r in engine.generate(reqs)]

    base = run(chaos=False)
    assert all(len(toks) == 6 for toks in base)
    assert base == run(chaos=False), "same seeds must replay identically"
    assert base == run(chaos=True), (
        "eviction/re-prefill changed a seeded sampling stream"
    )


def test_engine_rejects_oversized_prompt(lm):
    model, params = lm
    engine = ServeEngine(model, params, num_pages=4, page_size=4,
                         max_batch=2)  # context = 12 slots
    with pytest.raises(ValueError, match="context"):
        engine.generate(
            [Request(prompt=list(range(1, 15)), max_new_tokens=2)]
        )


def test_engine_capacity_finish(lm):
    """A request bounded by pool capacity is truncated with reason
    "capacity" instead of wedging the scheduler — and the truncated
    tokens still match the solo decode."""
    model, params = lm
    engine = ServeEngine(model, params, num_pages=4, page_size=4,
                         max_batch=2)  # 12 usable slots = max_context
    [res] = engine.generate(
        [Request(prompt=[3, 7, 2, 9], max_new_tokens=20)]
    )
    assert res.finish_reason == "capacity"
    # the last decode writes KV at slot max_context-1 and samples one
    # final token beyond it: max_context - len(prompt) + 1 tokens
    assert len(res.tokens) == 12 - 4 + 1
    want = solo_greedy(model, params, [3, 7, 2, 9], len(res.tokens))
    assert res.tokens == want


# -- robustness: deadlines, shedding, starvation, quarantine, drain --------
# (ISSUE 7; docs/serving.md#robustness)


class _Clock:
    """Manual host clock for exact deadline/drain timing in tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _tick_per_decode(engine, clock, dt=10.0, hook=None):
    """Advance the fake clock after every ragged dispatch (as if each
    step took ``dt`` seconds); ``hook(step_count)`` runs after the
    tick."""
    orig = engine._dispatch

    def ticking(seqs):
        orig(seqs)
        clock.t += dt
        if hook is not None:
            hook(engine.stats["decode_steps"])

    engine._dispatch = ticking


def test_deadline_expiry_mid_decode_frees_pages(lm):
    """A running request whose TTL blows mid-stream finishes 'expired'
    at the next decode boundary, its pages free immediately, and the
    tokens it DID emit match the solo oracle prefix; requests without a
    deadline are untouched."""
    model, params = lm
    clock = _Clock()
    engine = ServeEngine(model, params, num_pages=16, page_size=4,
                         max_batch=4, clock=clock)
    _tick_per_decode(engine, clock, dt=10.0)  # 10 "seconds" per step
    reqs = [
        Request(prompt=[3, 7, 2], max_new_tokens=12, request_id="dies",
                deadline_ms=25_000.0),
        Request(prompt=[11, 4, 9], max_new_tokens=12,
                request_id="lives"),
    ]
    by = {r.request_id: r for r in engine.generate(reqs)}
    assert by["dies"].finish_reason == "expired"
    assert 0 < len(by["dies"].tokens) < 12
    want = solo_greedy(model, params, [3, 7, 2], 12)
    assert by["dies"].tokens == want[: len(by["dies"].tokens)]
    assert by["lives"].finish_reason == "length"
    assert by["lives"].tokens == solo_greedy(model, params, [11, 4, 9],
                                             12)
    assert engine.stats["expired"] == 1
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_deadline_expiry_in_waiting_queue(lm):
    """A request that never leaves the waiting queue before its TTL
    expires at the ADMISSION boundary: zero tokens, no TTFT, no pages
    ever held."""
    model, params = lm
    clock = _Clock()
    engine = ServeEngine(model, params, num_pages=16, page_size=4,
                         max_batch=1, clock=clock)
    _tick_per_decode(engine, clock, dt=10.0)
    reqs = [
        Request(prompt=[3, 7, 2], max_new_tokens=6, request_id="runs"),
        Request(prompt=[5, 9], max_new_tokens=4, request_id="starves",
                deadline_ms=15_000.0),
    ]
    by = {r.request_id: r for r in engine.generate(reqs)}
    assert by["starves"].finish_reason == "expired"
    assert by["starves"].tokens == [] and by["starves"].ttft_ms is None
    assert by["runs"].finish_reason == "length"
    assert by["runs"].tokens == solo_greedy(model, params, [3, 7, 2], 6)
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_flood_shed_deterministic_and_bounded(lm):
    """2x-capacity flood against a bounded waiting queue: shed
    decisions are deterministic (reject-newest, same run to run), the
    queue never exceeds the bound, and admitted requests still match
    the solo oracle."""
    model, params = lm

    def run():
        engine = ServeEngine(model, params, num_pages=16, page_size=4,
                             max_batch=2, max_waiting=3)
        reqs = [
            Request(prompt=[2 + i, 5, 9], max_new_tokens=4,
                    request_id=f"r{i}")
            for i in range(9)
        ]
        return engine, reqs, engine.generate(reqs)

    e1, reqs, r1 = run()
    _, _, r2 = run()
    shed1 = [r.request_id for r in r1 if r.finish_reason == "shed"]
    shed2 = [r.request_id for r in r2 if r.finish_reason == "shed"]
    # reject-newest with free decode slots as headroom: on an idle
    # engine the first max_batch + max_waiting requests are kept, the
    # rest shed — the bound engages against OVERLOAD, never against
    # capacity the batch has free
    assert shed1 == [f"r{i}" for i in range(5, 9)]
    assert shed1 == shed2, "shed decisions must be deterministic"
    assert e1.stats["peak_waiting"] <= 3 + 2  # max_waiting + max_batch
    assert e1.stats["shed"] == 4
    for req, res in zip(reqs, r1):
        if res.finish_reason == "shed":
            assert res.tokens == [] and res.ttft_ms is None
        else:
            assert res.tokens == solo_greedy(model, params, req.prompt, 4)
    e1.pool.check_invariants()
    assert e1.pool.is_idle()


def test_starvation_freedom_under_chaos_promotion(lm):
    """Heavy seeded chaos preemption on a tiny pool with a small
    re-prefill budget: every admitted request still finishes (the
    budget promotes over-evicted sequences out of the victim scans) and
    every result stays token-identical to the solo oracle."""
    model, params = lm
    trng = np.random.RandomState(5)
    engine = ServeEngine(
        model, params, num_pages=7, page_size=4, max_batch=3,
        prefill_token_budget=16, request_retries=2,
        chaos_rate=0.6, chaos_rng=random.Random(5),
    )
    reqs = [
        Request(prompt=trng.randint(1, V, size=(int(n),)).tolist(),
                max_new_tokens=5, seed=i, eos_id=5, request_id=f"r{i}")
        for i, n in enumerate([3, 7, 5, 8, 4, 6])
    ]
    results = engine.generate(reqs)
    assert engine.stats["evictions"] >= 1
    for req, res in zip(reqs, results):
        assert res.finish_reason in ("eos", "length"), res
        want = solo_greedy(model, params, req.prompt, req.max_new_tokens,
                           eos=req.eos_id)
        assert res.tokens == want, (req.request_id, res.tokens, want)
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_scheduler_expire_and_promotion_units():
    from unicore_tpu.serve.scheduler import Scheduler

    pool = PagedKVPool(num_pages=8, page_size=4)
    sched = Scheduler(pool, max_batch=4, request_retries=1,
                      chaos_rate=1.0, chaos_rng=random.Random(0))
    a = sched.add(Request(prompt=[1, 2], max_new_tokens=2,
                          deadline_ms=100.0))
    b = sched.add(Request(prompt=[1, 2, 3], max_new_tokens=2))
    a.enqueued_at = b.enqueued_at = 0.0
    sched.admit()
    assert sched.expire(now=0.05) == []      # 50ms: TTL not blown
    assert sched.expire(now=0.2) == [a]      # 200ms > 100ms TTL
    assert a.finish_reason == "expired"
    pool.check_invariants()
    # promotion: an over-budget sequence is skipped by both victim scans
    c = sched.add(Request(prompt=[4, 5], max_new_tokens=2))
    c.enqueued_at = 0.0
    sched.admit()
    assert [s is b or s is c for s in sched.running] == [True, True]
    b.evictions = 1  # at the budget -> promoted
    assert sched._pick_victim() is c
    c.evictions = 1
    # everyone promoted: organic eviction falls back to LIFO (liveness)
    assert sched._pick_victim() is c
    # ...but chaos preemption skips promoted sequences entirely
    assert sched.chaos_preempt() is None
    # bounded add: free decode slots count as headroom (an idle engine
    # keeps max_batch + max_waiting); once the batch is saturated the
    # waiting line holds at exactly max_waiting
    pool2 = PagedKVPool(num_pages=16, page_size=4)
    s2 = Scheduler(pool2, max_batch=2, max_waiting=1)
    kept = [s2.add(Request(prompt=[1], max_new_tokens=1))
            for _ in range(4)]
    assert [q.finish_reason for q in kept] == [None, None, None, "shed"]
    s2.admit()  # 2 run, 1 waits: saturated
    late = s2.add(Request(prompt=[1], max_new_tokens=1))
    assert late.finish_reason == "shed" and len(s2.waiting) == 1
    pool2.check_invariants()


def test_poisoned_request_quarantined_survivors_identical(lm):
    """The fault-isolation oracle: one request's logits row is poisoned
    (NaN) inside the jitted step; it finishes 'failed' with its pages
    freed while every other request's tokens are bit-identical to solo
    decode."""
    model, params = lm
    trng = np.random.RandomState(11)
    prompts = [trng.randint(1, V, size=(n,)).tolist()
               for n in [3, 6, 4, 7]]
    engine = ServeEngine(model, params, num_pages=12, page_size=4,
                         max_batch=4, poison_requests=["r1"])
    reqs = [Request(prompt=p, max_new_tokens=6, eos_id=5,
                    request_id=f"r{i}") for i, p in enumerate(prompts)]
    by = {r.request_id: r for r in engine.generate(reqs)}
    assert by["r1"].finish_reason == "failed"
    assert by["r1"].tokens == []  # poisoned at prefill: nothing emitted
    assert engine.stats["quarantined"] == 1
    for i, p in enumerate(prompts):
        if i == 1:
            continue
        want = solo_greedy(model, params, p, 6, eos=5)
        assert by[f"r{i}"].tokens == want, (i, by[f"r{i}"].tokens, want)
        assert by[f"r{i}"].finish_reason in ("eos", "length")
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_poison_mid_stream_quarantines_on_decode_boundary(lm):
    """Poison arriving mid-stream (decode path, not prefill): the
    victim keeps its pre-fault tokens — which still match the solo
    prefix — and the batch survivors are untouched."""
    model, params = lm
    engine = ServeEngine(model, params, num_pages=12, page_size=4,
                         max_batch=2, poison_requests=["__armed__"])
    orig = engine._dispatch

    def arm_later(seqs):
        orig(seqs)
        if engine.stats["decode_steps"] == 2:
            engine._poison_ids = frozenset(["r0"])

    engine._dispatch = arm_later
    reqs = [Request(prompt=[3, 7, 2], max_new_tokens=8,
                    request_id="r0"),
            Request(prompt=[11, 4, 9, 8], max_new_tokens=8,
                    request_id="r1")]
    by = {r.request_id: r for r in engine.generate(reqs)}
    assert by["r0"].finish_reason == "failed"
    assert 0 < len(by["r0"].tokens) < 8
    assert by["r0"].tokens == solo_greedy(
        model, params, [3, 7, 2], 8)[: len(by["r0"].tokens)]
    assert by["r1"].finish_reason == "length"
    assert by["r1"].tokens == solo_greedy(model, params, [11, 4, 9, 8], 8)
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_host_fault_fails_inflight_not_engine(lm):
    """A host-side step exception fails the in-flight sequences with
    reason 'failed' and frees their pages; the engine survives and the
    next batch decodes clean."""
    model, params = lm
    engine = ServeEngine(model, params, num_pages=12, page_size=4,
                         max_batch=2)
    orig = engine._dispatch
    state = {"raised": False}

    def flaky(seqs):
        if not state["raised"] and engine.stats["decode_steps"] >= 1:
            state["raised"] = True
            raise RuntimeError("sampler exploded (host side)")
        orig(seqs)

    engine._dispatch = flaky
    reqs = [Request(prompt=[3, 7, 2], max_new_tokens=5,
                    request_id="a"),
            Request(prompt=[11, 4], max_new_tokens=5, request_id="b")]
    results = engine.generate(reqs)
    assert [r.finish_reason for r in results] == ["failed", "failed"]
    assert engine.stats["host_faults"] == 1
    engine.pool.check_invariants()
    assert engine.pool.is_idle()
    # the engine is still servable, token-identically
    [clean] = engine.generate(
        [Request(prompt=[6, 2, 9], max_new_tokens=5,
                 request_id="clean")])
    assert clean.tokens == solo_greedy(model, params, [6, 2, 9], 5)


def test_row_assembly_fault_fails_only_that_request(lm):
    """Per-request isolation survives the unified dispatch: a host-side
    fault in ONE row's assembly (a poisoned slot lookup for that
    sequence) fails only that request — the rest of the batch stays
    token-identical to the solo oracle."""
    model, params = lm
    engine = ServeEngine(model, params, num_pages=16, page_size=4,
                         max_batch=3)
    victim_sid = {}
    real_table = engine.pool.page_table

    def bad_table(sid):
        if sid == victim_sid.get("sid"):
            raise RuntimeError("corrupted per-sequence state")
        return real_table(sid)

    engine.pool.page_table = bad_table
    reqs = [Request(prompt=[3, 7, 2], max_new_tokens=5,
                    request_id="a"),
            Request(prompt=[11, 4, 9, 8], max_new_tokens=5,
                    request_id="bad"),
            Request(prompt=[6, 2], max_new_tokens=5, request_id="c")]
    seqs = engine.submit(reqs)
    victim_sid["sid"] = seqs[1].sid
    while engine.serve_step():
        pass
    by = {r.request_id: r for r in engine.collect_finished()}
    assert by["bad"].finish_reason == "failed"
    assert engine.stats["host_faults"] == 1
    for rid, prompt in (("a", [3, 7, 2]), ("c", [6, 2])):
        assert by[rid].finish_reason == "length"
        assert by[rid].tokens == solo_greedy(model, params, prompt, 5)
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_capacity_failfast_instead_of_livelock(lm):
    """Satellite fix: a request whose prompt+generated prefix can never
    fit the pool terminates with reason 'capacity' (counted in stats)
    instead of cycling the preempt-retry recovery forever; neighbors
    are unaffected."""
    model, params = lm
    engine = ServeEngine(model, params, num_pages=4, page_size=4,
                         max_batch=2)  # 3 usable pages = 12 slots
    sched = engine.scheduler
    good = sched.add(Request(prompt=[3, 7, 2], max_new_tokens=3,
                             request_id="fits"))
    bad = sched.add(Request(prompt=[2] * 8, max_new_tokens=4,
                            request_id="huge"))
    good.enqueued_at = bad.enqueued_at = 0.0
    # simulate a preempted-and-resumed request whose prefix outgrew the
    # whole pool (16 tokens -> 4 pages > 3 usable)
    bad.generated = [1] * 8
    engine._run_to_completion(sched)
    assert bad.finish_reason == "capacity"
    assert engine.stats["capacity_failfast"] == 1
    assert good.finish_reason == "length"
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_graceful_drain_sheds_within_timeout(lm):
    """SIGTERM-equivalent drain with drain_timeout=0: admission closes,
    waiting requests shed immediately, running ones shed at the next
    boundary past the deadline — partial tokens preserved (and still
    oracle-exact), pool idle, drain report emitted.  The engine stays
    drained afterwards."""
    import signal as _signal

    from unicore_tpu.resilience.preemption import GracefulShutdown

    model, params = lm
    sd = GracefulShutdown()  # not installed: programmatic trigger
    engine = ServeEngine(model, params, num_pages=16, page_size=4,
                         max_batch=2, shutdown=sd, drain_timeout=0.0)
    orig = engine._dispatch

    def trip(seqs):
        orig(seqs)
        if engine.stats["decode_steps"] == 2:
            sd.request(_signal.SIGTERM)

    engine._dispatch = trip
    reqs = [Request(prompt=[3 + i, 7, 2], max_new_tokens=10,
                    request_id=f"r{i}") for i in range(4)]
    results = engine.generate(reqs)
    assert all(r.finish_reason == "shed" for r in results)
    report = engine.drain_report
    assert report and report["requested"] and report["signal"] == "SIGTERM"
    assert report["pool_idle"] and engine.pool.is_idle()
    engine.pool.check_invariants()
    for req, res in zip(reqs, results):
        if res.tokens:
            want = solo_greedy(model, params, req.prompt, 10)
            assert res.tokens == want[: len(res.tokens)]
    # a drained engine sheds everything submitted later
    [late] = engine.generate([Request(prompt=[5, 5], max_new_tokens=2,
                                      request_id="late")])
    assert late.finish_reason == "shed"


def test_graceful_drain_finishes_inflight_within_timeout(lm):
    """With a generous drain_timeout, in-flight requests run their tail
    out and finish normally (solo-oracle-exact); only the never-admitted
    waiting request is shed."""
    from unicore_tpu.resilience.preemption import GracefulShutdown

    model, params = lm
    sd = GracefulShutdown()
    engine = ServeEngine(model, params, num_pages=16, page_size=4,
                         max_batch=2, shutdown=sd, drain_timeout=60.0)
    orig = engine._dispatch

    def trip(seqs):
        orig(seqs)
        if engine.stats["decode_steps"] == 1:
            sd.request()

    engine._dispatch = trip
    reqs = [Request(prompt=[3, 7, 2], max_new_tokens=6,
                    request_id="r0"),
            Request(prompt=[11, 4, 9], max_new_tokens=6,
                    request_id="r1"),
            Request(prompt=[6, 2], max_new_tokens=6, request_id="r2")]
    by = {r.request_id: r for r in engine.generate(reqs)}
    assert by["r2"].finish_reason == "shed"  # never admitted
    for rid, prompt in (("r0", [3, 7, 2]), ("r1", [11, 4, 9])):
        assert by[rid].finish_reason == "length"
        assert by[rid].tokens == solo_greedy(model, params, prompt, 6)
    assert engine.drain_report["deadline_exceeded"] is False
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


# -- ragged unification + shared-prefix dedup (ISSUE 13) -------------------


def test_chunked_prefill_matches_unchunked(lm):
    """A long prompt admitted in bounded-TTFT chunks emits tokens
    identical to the single-slice admission (and to the solo oracle) —
    chunked prefill is a latency feature, never an accuracy one."""
    model, params = lm
    trng = np.random.RandomState(17)
    prompts = [trng.randint(1, V, size=(n,)).tolist()
               for n in [23, 7, 30, 12]]

    def run(chunk):
        engine = ServeEngine(model, params, num_pages=24, page_size=4,
                             max_batch=4, prefill_chunk=chunk)
        reqs = [Request(prompt=p, max_new_tokens=5, seed=i, eos_id=5,
                        request_id=f"r{i}")
                for i, p in enumerate(prompts)]
        return [r.tokens for r in engine.generate(reqs)], engine

    base, _ = run(chunk=64)          # every prompt in one slice
    small, eng = run(chunk=4)        # 23-token prompt -> 6 slices
    assert base == small
    for toks, p in zip(base, prompts):
        assert toks == solo_greedy(model, params, p, 5, eos=5)
    assert eng.prefill_chunk == 4
    eng.pool.check_invariants()


def test_split_dispatch_matches_unified(lm):
    """The bench A/B baseline (unified=False: prefill rows and decode
    rows as two separate programs per step) is token-identical to the
    unified mixed dispatch — the comparison isolates performance."""
    model, params = lm
    trng = np.random.RandomState(3)
    prompts = [trng.randint(1, V, size=(n,)).tolist()
               for n in [3, 9, 6, 12, 5]]

    def run(unified):
        engine = ServeEngine(model, params, num_pages=16, page_size=4,
                             max_batch=3, unified=unified)
        reqs = [Request(prompt=p, max_new_tokens=6, seed=i,
                        request_id=f"r{i}")
                for i, p in enumerate(prompts)]
        return [r.tokens for r in engine.generate(reqs)]

    assert run(True) == run(False)


def test_pool_prefix_dedup_refcounts_and_reclaim():
    """Dedup invariants: a second sequence sharing a registered prefix
    references the SAME full pages (refcount 2), the partial tail page
    is never shared, freeing drops references without freeing shared
    pages, and a fully-released registered page parks in the cache
    (reclaimable, pool still idle)."""
    pool = PagedKVPool(num_pages=16, page_size=4)
    toks = list(range(100, 118))  # 18 tokens: 4 full pages + tail of 2
    t_a = pool.alloc("a", len(toks), tokens=toks)
    assert pool.cached_tokens("a") == 0  # nothing registered yet
    pool.register_prefix("a", toks)
    pool.check_invariants()
    t_b = pool.alloc("b", len(toks), tokens=toks)
    pool.check_invariants()
    # the 4 full pages are shared by reference; the tail is private
    assert t_b[:4] == t_a[:4]
    assert t_b[4] != t_a[4]
    assert pool.cached_tokens("b") == 16
    assert pool.prefix_stats["hits"] == 1
    assert pool.prefix_stats["tokens_saved"] == 16
    # freeing the REGISTRANT keeps the shared pages live for b
    pool.free("a")
    pool.check_invariants()
    assert pool.page_table("b")[:4] == t_a[:4]
    # freeing b parks the registered pages in the cache: reclaimable
    # capacity, pool idle, and a third sequence still hits
    pool.free("b")
    pool.check_invariants()
    assert pool.is_idle()
    assert pool.num_free_pages == pool.num_usable_pages
    t_c = pool.alloc("c", len(toks), tokens=toks)
    assert t_c[:4] == t_a[:4] and pool.prefix_stats["hits"] == 2
    pool.free("c")
    pool.check_invariants()


def test_pool_page_aligned_prefix_keeps_tail_private():
    """A prompt whose full length is page-aligned AND fully indexed
    must still re-prefill its last page privately (at least one token
    — the one whose logits seed sampling — is never dedup'd), so no
    sequence ever writes into a shared page: the CoW-by-recompute
    contract."""
    pool = PagedKVPool(num_pages=16, page_size=4)
    toks = list(range(200, 216))  # exactly 4 pages
    t_a = pool.alloc("a", len(toks), tokens=toks)
    pool.register_prefix("a", toks)
    t_b = pool.alloc("b", len(toks), tokens=toks)
    assert pool.cached_tokens("b") == 12  # capped at len - 1 -> 3 pages
    assert t_b[:3] == t_a[:3] and t_b[3] != t_a[3]
    # every write position b issues (>= cached_tokens) lands in a
    # page b owns exclusively
    for pos in range(pool.cached_tokens("b"), len(toks)):
        slot = pool.slot("b", pos)
        assert slot // pool.page_size not in t_a, (pos, slot)
    pool.free("a")
    pool.free("b")
    pool.check_invariants()


def test_engine_warm_prefix_skips_prefill_tokens(lm):
    """The tentpole property: a repeat of a warm shared prefix becomes
    a page-table lookup — the second request's ragged prefill starts
    past the shared pages — while its tokens stay solo-oracle exact."""
    model, params = lm
    trng = np.random.RandomState(29)
    system = trng.randint(1, V, size=(18,)).tolist()
    tails = [trng.randint(1, V, size=(4,)).tolist() for _ in range(2)]
    engine = ServeEngine(model, params, num_pages=24, page_size=4,
                         max_batch=2, prefill_chunk=8)
    [cold] = engine.generate(
        [Request(prompt=system + tails[0], max_new_tokens=4,
                 request_id="cold")])
    assert engine.pool.prefix_stats["hits"] == 0
    [warm] = engine.generate(
        [Request(prompt=system + tails[1], max_new_tokens=4,
                 request_id="warm")])
    # 18 shared tokens -> 4 full pages (16 tokens) dedup'd
    assert engine.pool.prefix_stats["hits"] == 1
    assert engine.pool.prefix_stats["tokens_saved"] == 16
    assert engine.stats["prefix_hits"] == 1
    snap = engine.load_snapshot()
    assert snap["prefix_hits"] == 1 and snap["prefix_hit_rate"] > 0
    for res, tail in zip((cold, warm), tails):
        assert res.tokens == solo_greedy(model, params, system + tail, 4)
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


def test_prefix_cache_on_off_and_eviction_deterministic(lm):
    """Prefix-cache determinism: the same request stream emits
    IDENTICAL tokens with the cache on, off, and across cache eviction
    pressure (a tiny pool forces cached pages to be reclaimed and
    re-registered) — dedup is a capacity feature, never an accuracy
    one."""
    model, params = lm
    trng = np.random.RandomState(41)
    system = trng.randint(1, V, size=(9,)).tolist()
    reqs_spec = [(system + trng.randint(1, V, size=(3,)).tolist(), i)
                 for i in range(5)]

    def run(prefix_cache, num_pages):
        engine = ServeEngine(model, params, num_pages=num_pages,
                             page_size=4, max_batch=2,
                             prefix_cache=prefix_cache)
        reqs = [Request(prompt=p, max_new_tokens=4, seed=i,
                        request_id=f"r{i}") for p, i in reqs_spec]
        out = [r.tokens for r in engine.generate(reqs)]
        engine.pool.check_invariants()
        return out, engine

    base, _ = run(prefix_cache=False, num_pages=24)
    cached, e1 = run(prefix_cache=True, num_pages=24)
    tight, e2 = run(prefix_cache=True, num_pages=8)  # eviction pressure
    assert base == cached == tight
    assert e1.pool.prefix_stats["hits"] >= 1
    # the tight pool really did evict cached pages (the determinism
    # claim is vacuous otherwise)
    assert e2.pool.prefix_stats["cache_evictions"] >= 1
    # and two identical tight runs make identical hit/miss decisions
    tight2, e3 = run(prefix_cache=True, num_pages=8)
    assert tight2 == tight
    assert e3.pool.prefix_stats == e2.pool.prefix_stats


def test_auto_prefill_chunk_consults_tuner(lm):
    """prefill_chunk=0 (auto) takes a measured chunked-admission
    verdict for the engine's ragged bucket; an explicit chunk always
    wins, and no verdict means the default."""
    from unicore_tpu.ops import tuning
    from unicore_tpu.serve.engine import DEFAULT_PREFILL_CHUNK

    model, params = lm
    base = ServeEngine(model, params, num_pages=16, page_size=4,
                       max_batch=2)
    assert base.prefill_chunk == DEFAULT_PREFILL_CHUNK
    with tuning.forced_config(
            "ragged_paged_attention",
            {"pages_per_block": 1, "prefill_chunk": 8}):
        tuned = ServeEngine(model, params, num_pages=16, page_size=4,
                            max_batch=2)
        explicit = ServeEngine(model, params, num_pages=16, page_size=4,
                               max_batch=2, prefill_chunk=16)
    assert tuned.prefill_chunk == 8
    assert tuned.serve_step_widths() == (1, 8)
    assert explicit.prefill_chunk == 16


def test_quarantined_prefix_sharer_leaves_survivor_exact(lm):
    """A poisoned request whose pages are prefix-SHARED is quarantined
    while the survivor sharing the prefix stays token-identical — the
    quarantine drops one reference, never the shared pages."""
    model, params = lm
    trng = np.random.RandomState(7)
    system = trng.randint(1, V, size=(10,)).tolist()
    t0, t1 = ([int(x) for x in trng.randint(1, V, size=(3,))]
              for _ in range(2))
    engine = ServeEngine(model, params, num_pages=24, page_size=4,
                         max_batch=2, poison_requests=["bad"])
    [good0] = engine.generate(
        [Request(prompt=system + t0, max_new_tokens=4,
                 request_id="seed-prefix")])
    by = {r.request_id: r for r in engine.generate([
        Request(prompt=system + t1, max_new_tokens=4,
                request_id="bad"),
        Request(prompt=system + t0, max_new_tokens=4,
                request_id="survivor"),
    ])}
    assert engine.pool.prefix_stats["hits"] >= 2  # both shared pages
    assert by["bad"].finish_reason == "failed"
    want = solo_greedy(model, params, system + t0, 4)
    assert good0.tokens == want
    assert by["survivor"].tokens == want
    assert by["survivor"].finish_reason in ("eos", "length")
    engine.pool.check_invariants()
    assert engine.pool.is_idle()


# -- CLI -------------------------------------------------------------------


def test_serve_cli_demo(tmp_path):
    import json

    from unicore_tpu.serve.cli import main

    out = tmp_path / "serve.json"
    rc = main([
        "--demo", "--num-requests", "3", "--max-new-tokens", "5",
        "--page-size", "4", "--num-pages", "16", "--max-batch", "3",
        "--prompt-len-range", "3,9", "--json", str(out),
        "--max-waiting", "8", "--drain-timeout", "5",
    ])
    assert rc == 0
    report = json.loads(out.read_text())
    assert len(report["results"]) == 3
    for res in report["results"]:
        assert res["finish_reason"] in ("eos", "length", "capacity")
        assert len(res["tokens"]) == 5
    assert report["stats"]["generated_tokens"] == 15
    # robustness surface: no drain happened, the pool ended clean, and
    # the lifecycle counters rode along at zero
    assert report["drain"] is None and report["pool_clean"] is True
    for key in ("shed", "expired", "quarantined", "capacity_failfast"):
        assert report["stats"][key] == 0, (key, report["stats"])
